"""Builds libbrpc_tpu_native.so from src/*.cc with g++.

Invoked automatically on first import of brpc_tpu.native (and rebuilt when
any source is newer than the library). Can also be run directly:
    python -m brpc_tpu.native.build

Sanitizer lane: with BRPC_TPU_SANITIZE set (e.g. "address,undefined"),
both artifacts build under the requested sanitizers into SEPARATE
``.san.so`` files with their own staleness cache, so the fast lane's
plain artifacts are never clobbered by an instrumented build (and vice
versa). Loading an ASan-instrumented extension requires the sanitizer
runtime to be FIRST in the link order, which a stock CPython is not —
run the interpreter with the env from ``sanitizer_env()`` (LD_PRELOAD
of libasan/libubsan + leak detection off for CPython's arena leaks).
The tier-2 test lane (tests/test_sanitizer_lane.py) and the preflight
gate (tools/preflight.py --gate) both drive this path.
"""

from __future__ import annotations

import os
import subprocess
import sys
from typing import List, Optional, Sequence, Tuple

_DIR = os.path.dirname(os.path.abspath(__file__))
SRC_DIR = os.path.join(_DIR, "src")
LIB_PATH = os.path.join(_DIR, "libbrpc_tpu_native.so")

CXX = os.environ.get("CXX", "g++")
CXXFLAGS = ["-O2", "-g", "-std=c++17", "-fPIC", "-shared", "-pthread",
            "-Wall", "-Wextra", "-fno-exceptions"]

# supported BRPC_TPU_SANITIZE tokens -> compiler flag groups
_SANITIZERS = {
    "address": ["-fsanitize=address"],
    "undefined": ["-fsanitize=undefined"],
    "thread": ["-fsanitize=thread"],
}
_SAN_COMMON = ["-fno-omit-frame-pointer", "-fno-sanitize-recover=all"]
# sanitizer token -> runtime library the host interpreter must preload
_SAN_RUNTIMES = {"address": "libasan.so", "undefined": "libubsan.so",
                 "thread": "libtsan.so"}


# fastcore.cc is a CPython extension module (needs Python headers,
# exports PyInit__brpc_fastcore) — built separately from the C-ABI lib.
# ring.cc (the batched-syscall event lane) rides this build so the
# sanitizer lane's .san.so instruments it together with the fd loops.
FASTCORE_SRCS = ("fastcore.cc", "respool.cc", "queues.cc", "httpparse.cc",
                 "ring.cc")
FASTCORE_PATH = os.path.join(_DIR, "_brpc_fastcore.so")


def sanitize_mode(env: Optional[str] = None) -> Tuple[str, ...]:
    """Parse BRPC_TPU_SANITIZE (or the given string) into a normalized
    sanitizer tuple; unknown tokens raise so a typo can't silently run
    the uninstrumented lane while claiming sanitizer coverage."""
    raw = os.environ.get("BRPC_TPU_SANITIZE", "") if env is None else env
    out = []
    for tok in raw.replace(";", ",").split(","):
        tok = tok.strip().lower()
        if not tok:
            continue
        if tok not in _SANITIZERS:
            raise ValueError(
                f"BRPC_TPU_SANITIZE: unknown sanitizer {tok!r} "
                f"(known: {', '.join(sorted(_SANITIZERS))})")
        if tok not in out:
            out.append(tok)
    return tuple(out)


def check_no_native_conflict(san: Sequence[str]) -> None:
    """Raise when BRPC_TPU_NO_NATIVE would silently drop an active
    sanitize mode: disabling the native lane runs pure Python while
    the env claims sanitizer coverage."""
    if san:
        raise RuntimeError(
            "BRPC_TPU_SANITIZE=%s conflicts with BRPC_TPU_NO_NATIVE: "
            "disabling the native lane would run pure Python while "
            "the env claims sanitizer coverage" % ",".join(san))


def sanitized_load_failure(san: Sequence[str],
                           what: str) -> RuntimeError:
    """The error for a sanitized artifact that failed to build or
    load — raised instead of the silent uninstrumented fallback."""
    return RuntimeError(
        "BRPC_TPU_SANITIZE=%s is set but the sanitized %s failed to "
        "build or load; refusing the uninstrumented pure-Python "
        "fallback. Run the interpreter with the env from "
        "brpc_tpu.native.build.sanitizer_env() (LD_PRELOAD of the "
        "sanitizer runtimes)." % (",".join(san), what))


def sanitize_changed_error(latched: Optional[str]) -> RuntimeError:
    """The error for BRPC_TPU_SANITIZE changing AFTER a native loader
    latched its cache: the cached artifact no longer matches the
    requested instrumentation."""
    cur = os.environ.get("BRPC_TPU_SANITIZE", "")
    return RuntimeError(
        "BRPC_TPU_SANITIZE changed to %r after the native loader "
        "latched under %r: the cached artifact no longer matches the "
        "requested instrumentation — set the env before the first "
        "native use, or restart the process" % (cur, latched or ""))


def _san_path(base: str, san: Sequence[str]) -> str:
    """Artifact path for a sanitizer combo: foo.so -> foo.san.so (one
    cache per combo would be overkill; the .san artifact records its
    combo in a sidecar tag so a different combo forces a rebuild)."""
    if not san:
        return base
    root, ext = os.path.splitext(base)
    return f"{root}.san{ext}"


def _cxxflags(san: Sequence[str]) -> List[str]:
    """Base flags + sanitizer instrumentation for a build."""
    flags = list(CXXFLAGS)
    for tok in san:
        flags.extend(_SANITIZERS[tok])
    if san:
        flags.extend(_SAN_COMMON)
    return flags


def _tag_path(out_path: str) -> str:
    return out_path + ".tag"


def _stale(out_path: str, srcs, san: Sequence[str] = ()) -> bool:
    if not os.path.exists(out_path):
        return True
    if san:
        try:
            with open(_tag_path(out_path)) as f:
                if f.read().strip() != ",".join(san):
                    return True
        except OSError:
            return True
    mtime = os.path.getmtime(out_path)
    return any(os.path.getmtime(s) > mtime for s in srcs)


def _write_tag(out_path: str, san: Sequence[str]) -> None:
    if san:
        with open(_tag_path(out_path), "w") as f:
            f.write(",".join(san))


def sources() -> list:
    # fastcore.cc + httpparse.cc + ring.cc need Python headers: they
    # belong to the extension module build only
    return sorted(
        os.path.join(SRC_DIR, f) for f in os.listdir(SRC_DIR)
        if f.endswith(".cc") and f not in ("fastcore.cc", "httpparse.cc",
                                           "ring.cc")
    )


def needs_build() -> bool:
    san = sanitize_mode()
    return _stale(_san_path(LIB_PATH, san), sources(), san)


def build(force: bool = False,
          sanitize: Optional[Sequence[str]] = None) -> str:
    """Compile if stale; returns the library path. Raises on failure.
    ``sanitize`` defaults to the BRPC_TPU_SANITIZE env setting."""
    san = sanitize_mode() if sanitize is None else tuple(sanitize)
    out = _san_path(LIB_PATH, san)
    srcs = sources()
    if not force and not _stale(out, srcs, san):
        return out
    cmd = [CXX, *_cxxflags(san), "-o", out, *srcs]
    # graftlint: disable=blocking-under-lock -- the loader latch lock IS
    # the single-flight compile guard: a concurrent importer must wait
    # for the one compiler run, not race a second cc1plus at the cache
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(
            f"native build failed:\n$ {' '.join(cmd)}\n{proc.stderr}")
    _write_tag(out, san)
    return out


def build_fastcore(force: bool = False,
                   sanitize: Optional[Sequence[str]] = None) -> str:
    """Compile the _brpc_fastcore CPython extension if stale."""
    import sysconfig
    san = sanitize_mode() if sanitize is None else tuple(sanitize)
    out = _san_path(FASTCORE_PATH, san)
    srcs = [os.path.join(SRC_DIR, f) for f in FASTCORE_SRCS]
    if not force and not _stale(out, srcs, san):
        return out
    include = sysconfig.get_paths()["include"]
    cmd = [CXX, *_cxxflags(san), f"-I{include}", "-o", out, *srcs]
    # graftlint: disable=blocking-under-lock -- same single-flight
    # compile discipline as build(): the fastcore loader lock must hold
    # through the compiler run so importers share one artifact
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(
            f"fastcore build failed:\n$ {' '.join(cmd)}\n{proc.stderr}")
    _write_tag(out, san)
    return out


def _runtime_lib(name: str) -> Optional[str]:
    """Absolute path of a sanitizer runtime (libasan.so / libubsan.so)
    via the compiler, or None when the toolchain lacks it."""
    try:
        proc = subprocess.run([CXX, f"-print-file-name={name}"],
                              capture_output=True, text=True, timeout=30)
    except (OSError, subprocess.TimeoutExpired):
        return None
    path = proc.stdout.strip()
    return path if path and os.path.isabs(path) and os.path.exists(path) \
        else None


def sanitizer_toolchain_missing(
        san: Sequence[str] = ("address", "undefined")) -> List[str]:
    """Names of the toolchain pieces missing for an instrumented build
    (empty list = ready): the compiler plus each requested sanitizer's
    runtime. The single probe authority for the preflight gate and the
    tier-2 test lane."""
    import shutil
    missing = []
    if shutil.which(CXX) is None:
        missing.append(CXX)
    for tok in san:
        lib = _SAN_RUNTIMES.get(tok)
        if lib and _runtime_lib(lib) is None:
            missing.append(lib)
    return missing


def sanitizer_env(san: Optional[Sequence[str]] = None) -> dict:
    """Environment overlay for RUNNING python against .san artifacts:
    LD_PRELOAD of the sanitizer runtimes (they must initialize before
    the interpreter) and options tuned for a CPython host process
    (leak detection off — the interpreter's arenas never fully free;
    abort on any real ASan/UBSan diagnosis so tests fail loudly).
    Returns {} when no sanitizer is configured."""
    san = sanitize_mode() if san is None else tuple(san)
    if not san:
        return {}
    preload = []
    for tok in san:
        lib = _SAN_RUNTIMES.get(tok)
        p = _runtime_lib(lib) if lib else None
        if p:
            preload.append(p)
    env = {
        "BRPC_TPU_SANITIZE": ",".join(san),
        "ASAN_OPTIONS": "detect_leaks=0:abort_on_error=1:"
                        "allocator_may_return_null=1",
        "UBSAN_OPTIONS": "halt_on_error=1:abort_on_error=1:"
                         "print_stacktrace=1",
    }
    if preload:
        prior = os.environ.get("LD_PRELOAD", "")
        env["LD_PRELOAD"] = " ".join(preload + ([prior] if prior else []))
    return env


if __name__ == "__main__":
    force = "--force" in sys.argv
    path = build(force=force)
    print(path)
    print(build_fastcore(force=force))
    if sanitize_mode():
        print("sanitizers:", ",".join(sanitize_mode()))
