"""Builds libbrpc_tpu_native.so from src/*.cc with g++.

Invoked automatically on first import of brpc_tpu.native (and rebuilt when
any source is newer than the library). Can also be run directly:
    python -m brpc_tpu.native.build
"""

from __future__ import annotations

import os
import subprocess
import sys

_DIR = os.path.dirname(os.path.abspath(__file__))
SRC_DIR = os.path.join(_DIR, "src")
LIB_PATH = os.path.join(_DIR, "libbrpc_tpu_native.so")

CXX = os.environ.get("CXX", "g++")
CXXFLAGS = ["-O2", "-g", "-std=c++17", "-fPIC", "-shared", "-pthread",
            "-Wall", "-Wextra", "-fno-exceptions"]


# fastcore.cc is a CPython extension module (needs Python headers,
# exports PyInit__brpc_fastcore) — built separately from the C-ABI lib
FASTCORE_SRCS = ("fastcore.cc", "respool.cc", "queues.cc", "httpparse.cc")
FASTCORE_PATH = os.path.join(_DIR, "_brpc_fastcore.so")


def sources() -> list:
    # fastcore.cc + httpparse.cc need Python headers: they belong to the
    # extension module build only
    return sorted(
        os.path.join(SRC_DIR, f) for f in os.listdir(SRC_DIR)
        if f.endswith(".cc") and f not in ("fastcore.cc", "httpparse.cc")
    )


def _stale(out_path: str, srcs) -> bool:
    if not os.path.exists(out_path):
        return True
    mtime = os.path.getmtime(out_path)
    return any(os.path.getmtime(s) > mtime for s in srcs)


def needs_build() -> bool:
    return _stale(LIB_PATH, sources())


def build(force: bool = False) -> str:
    """Compile if stale; returns the library path. Raises on failure."""
    if not force and not needs_build():
        return LIB_PATH
    cmd = [CXX, *CXXFLAGS, "-o", LIB_PATH, *sources()]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(
            f"native build failed:\n$ {' '.join(cmd)}\n{proc.stderr}")
    return LIB_PATH


def build_fastcore(force: bool = False) -> str:
    """Compile the _brpc_fastcore CPython extension if stale."""
    import sysconfig
    srcs = [os.path.join(SRC_DIR, f) for f in FASTCORE_SRCS]
    if not force and not _stale(FASTCORE_PATH, srcs):
        return FASTCORE_PATH
    include = sysconfig.get_paths()["include"]
    cmd = [CXX, *CXXFLAGS, f"-I{include}", "-o", FASTCORE_PATH, *srcs]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(
            f"fastcore build failed:\n$ {' '.join(cmd)}\n{proc.stderr}")
    return FASTCORE_PATH


if __name__ == "__main__":
    path = build(force="--force" in sys.argv)
    print(path)
    print(build_fastcore(force="--force" in sys.argv))
