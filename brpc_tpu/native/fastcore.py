"""Loader for the _brpc_fastcore CPython extension.

``get()`` returns the extension module or None (no compiler, build
failure, or BRPC_TPU_NO_NATIVE set) — every consumer keeps a pure-Python
fallback, mirroring how the ctypes library is loaded
(brpc_tpu/native/__init__.py). The extension puts the native cores on
the per-call hot path: see src/fastcore.cc for what maps where.
"""

from __future__ import annotations

import importlib.util
import os
import threading

_lock = threading.Lock()
_mod = None
_tried = False


def get():
    global _mod, _tried
    if _mod is not None or _tried:
        return _mod
    with _lock:
        if _mod is not None or _tried:
            return _mod
        _tried = True
        if os.environ.get("BRPC_TPU_NO_NATIVE"):
            return None
        try:
            from brpc_tpu.native.build import build_fastcore
            path = build_fastcore()
            spec = importlib.util.spec_from_file_location(
                "_brpc_fastcore", path)
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            _mod = mod
        except Exception:
            _mod = None
    return _mod


def available() -> bool:
    return get() is not None
