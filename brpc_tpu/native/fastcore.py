"""Loader for the _brpc_fastcore CPython extension.

``get()`` returns the extension module or None (no compiler, build
failure, or BRPC_TPU_NO_NATIVE set) — every consumer keeps a pure-Python
fallback, mirroring how the ctypes library is loaded
(brpc_tpu/native/__init__.py). The extension puts the native cores on
the per-call hot path: see src/fastcore.cc for what maps where.
"""

from __future__ import annotations

import importlib.util
import os
import threading

_lock = threading.Lock()
_mod = None
_tried = False
# BRPC_TPU_SANITIZE value the cache was latched under: a change after
# latching must raise, not silently serve the mismatched artifact
_latched_san = None


def get():
    global _mod, _tried, _latched_san
    if _mod is not None or _tried:
        if os.environ.get("BRPC_TPU_SANITIZE", "") != _latched_san:
            from brpc_tpu.native.build import sanitize_changed_error
            raise sanitize_changed_error(_latched_san)
        return _mod
    with _lock:
        if _mod is not None or _tried:
            return _mod
        # validate BRPC_TPU_SANITIZE before latching _tried, before the
        # broad except, and before the BRPC_TPU_NO_NATIVE short-circuit:
        # a typo must raise — on EVERY call, not just the first — never
        # silently drop both native and sanitizer coverage via the
        # pure-Python fallback
        from brpc_tpu.native.build import (build_fastcore,
                                           check_no_native_conflict,
                                           sanitize_mode,
                                           sanitized_load_failure)
        san = sanitize_mode()
        if os.environ.get("BRPC_TPU_NO_NATIVE"):
            check_no_native_conflict(san)
            _latched_san = ""
            _tried = True
            return None
        try:
            path = build_fastcore()
            spec = importlib.util.spec_from_file_location(
                "_brpc_fastcore", path)
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            _mod = mod
        except Exception as e:
            _mod = None
            if san:
                # a VALID sanitize mode whose artifact fails to
                # build/load must be just as loud as a typo, and must
                # not latch _tried: proceeding on pure Python would
                # pass the run off as sanitized with zero coverage
                raise sanitized_load_failure(
                    san, "fastcore extension") from e
        _latched_san = os.environ.get("BRPC_TPU_SANITIZE", "")
        _tried = True
    return _mod


def available() -> bool:
    return get() is not None
