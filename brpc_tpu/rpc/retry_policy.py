"""Pluggable retry decision (brpc/retry_policy.h).

The reference consults a RetryPolicy in OnVersionedRPCReturned
(controller.cpp:634) for every failed attempt — transport failures AND
server-returned errors — so apps can widen (retry an app-specific
status) or narrow (never retry writes) the default. Default policy
mirrors RpcRetryPolicy::DoRetry (retry_policy.cpp:25): retry errors
that plausibly mean "another server / another moment would succeed",
never client-fatal ones (bad request, auth, deadline).
"""

from __future__ import annotations

import random
import threading
import weakref

from brpc_tpu.rpc import errno_codes as berr


class RetryPolicy:
    """Subclass and override do_retry; return True to retry the attempt
    (the controller carries error_code/error_text of the failure)."""

    def do_retry(self, cntl) -> bool:
        raise NotImplementedError

    def retry_backoff_s(self, cntl) -> float:
        """Seconds to wait before the next attempt (0 = immediate, the
        default — existing latency behavior is unchanged unless a
        policy opts in). ``cntl.current_try`` is the 0-based index of
        the attempt that just failed. The channel clamps the wait to
        the call's remaining deadline budget."""
        return 0.0


class RpcRetryPolicy(RetryPolicy):
    """Default: transport/availability errors retry, semantic errors
    don't."""

    RETRYABLE = frozenset({
        berr.EFAILEDSOCKET,   # connection broke mid-call
        berr.ECLOSE,          # peer closed
        berr.ELOGOFF,         # server stopping: another replica may serve
        berr.ELIMIT,          # concurrency limiter rejected: retry elsewhere
        berr.EOVERCROWDED,    # write buffers full
        berr.EPRIORITYSHED,   # below one node's admission threshold:
        #                       thresholds are per-node — another
        #                       replica may still admit this class.
        #                       Client-LOCAL doomed-send sheds take
        #                       the same path (µs per excluded pick,
        #                       Channel._issue_rpc), so one stalled
        #                       node never dooms a call its healthy
        #                       siblings would serve
    })

    def do_retry(self, cntl) -> bool:
        return cntl.error_code in self.RETRYABLE


class RetryBackoffPolicy(RpcRetryPolicy):
    """Exponential backoff **with jitter** between retry attempts (the
    reference's ``retry_backoff`` policy family, retry_policy.h):
    attempt N waits ``base_ms * 2**N``, capped at ``max_ms``, then
    spread by ``jitter`` (a ±fraction — attempt storms from correlated
    failures must not re-synchronize on the retry schedule). The
    channel additionally clamps every wait to the call's remaining
    deadline budget, so opting in can never push a call past its own
    deadline.

    ``rng`` is injectable for deterministic tests (chaos lane);
    ``retryable`` optionally overrides the retry decision (a callable
    ``(cntl)->bool``), defaulting to the standard transport-error set.
    """

    def __init__(self, base_ms: float = 20.0, max_ms: float = 1000.0,
                 jitter: float = 0.5, rng: random.Random | None = None,
                 retryable=None):
        if not 0.0 <= jitter <= 1.0:
            raise ValueError("jitter must be within [0, 1]")
        self.base_ms = float(base_ms)
        self.max_ms = float(max_ms)
        self.jitter = float(jitter)
        self._rng = rng or random.Random()
        self._retryable = retryable

    def do_retry(self, cntl) -> bool:
        if self._retryable is not None:
            return bool(self._retryable(cntl))
        return super().do_retry(cntl)

    def retry_backoff_s(self, cntl) -> float:
        b = min(self.base_ms * (2.0 ** cntl.current_try), self.max_ms)
        if self.jitter:
            # b * [1-jitter, 1+jitter): full spread around the nominal
            b *= 1.0 + self.jitter * (2.0 * self._rng.random() - 1.0)
        return b / 1e3


class RetryBudget:
    """Per-channel retry token bucket (the gRPC retryThrottling shape,
    via *The Tail at Scale*'s rule that hedges/retries must never
    amplify overload): every failed attempt drains one token, every
    successful call slowly refills ``token_ratio``, and while the
    bucket sits at or below half its capacity the channel suppresses
    retries AND hedges (``retry_throttled`` bvar). Under a cluster
    brown-out the buckets of every client drain within the first few
    dozen failures, so the cluster sees ~1x the offered load instead
    of (1 + max_retry)x — the retry storm that turns a brown-out into
    an outage never forms. Healthy traffic keeps the bucket pinned at
    capacity; an isolated failure burst (one node dying) spends a few
    tokens and retries normally.

    Opt in per channel with ``ChannelOptions(retry_budget=True)`` (or
    an instance for custom sizing)."""

    def __init__(self, max_tokens: float = 100.0,
                 token_ratio: float = 0.1):
        if max_tokens <= 0:
            raise ValueError("max_tokens must be > 0")
        self._max = float(max_tokens)
        self._tokens = self._max
        self._ratio = float(token_ratio)
        self._threshold = self._max / 2.0
        self._lock = threading.Lock()
        _budgets.add(self)
        _ensure_tokens_var()

    def drain(self) -> None:
        with self._lock:
            self._tokens = max(0.0, self._tokens - 1.0)

    def refill(self) -> None:
        with self._lock:
            if self._tokens < self._max:
                self._tokens = min(self._max, self._tokens + self._ratio)

    def throttled(self) -> bool:
        """True while the bucket is at/below half capacity: the channel
        must not launch retries or arm hedges."""
        with self._lock:
            return self._tokens <= self._threshold

    def tokens(self) -> float:
        with self._lock:
            return self._tokens

    def snapshot(self) -> dict:
        with self._lock:
            return {"tokens": round(self._tokens, 2),
                    "max_tokens": self._max,
                    "token_ratio": self._ratio,
                    "throttled": self._tokens <= self._threshold}

    @staticmethod
    def resolve(spec) -> "RetryBudget | None":
        """ChannelOptions.retry_budget: None/False = off, True =
        defaults, an instance = itself."""
        if spec is None or spec is False:
            return None
        if spec is True:
            return RetryBudget()
        if isinstance(spec, RetryBudget):
            return spec
        raise TypeError(f"not a retry budget: {spec!r}")


# live budgets, weakly held — the /status saturation pane and the
# merged shard views report the process's MOST DRAINED bucket
# (retry_tokens: min across channels; a healthy fleet pins at max)
_budgets: "weakref.WeakSet[RetryBudget]" = weakref.WeakSet()
_tokens_var_exposed = False

# channel-group budgets (ISSUE 14): every channel a process holds to
# the same cluster shares ONE token bucket, closing the PR 10 "one
# process, many channels, one cluster" amplification hole — N channels
# with private buckets give a brown-out N x max_tokens of retry fuel.
# Keyed by ChannelOptions(budget_group=...); strongly held (the group
# is a process-lifetime identity, like the bvar it feeds).
_group_budgets: dict = {}
_group_lock = threading.Lock()


def shared_retry_budget(group: str, spec=True) -> RetryBudget:
    """The group's shared RetryBudget, created from ``spec`` on first
    use (True = defaults, an instance = its sizing). First channel
    wins the sizing; later channels join the EXISTING bucket whatever
    spec they carry — two sizings for one cluster would mean two
    different ideas of how much retry fuel that cluster can absorb.
    Built outside the lock (the bucket's constructor exposes a bvar,
    and bvar registration must never nest under this registry lock)."""
    cur = _group_budgets.get(group)
    if cur is not None:
        return cur
    made = RetryBudget.resolve(True if spec is None else spec)
    with _group_lock:
        cur = _group_budgets.get(group)
        if cur is None:
            cur = _group_budgets[group] = made
    return cur


def budget_group_snapshot() -> dict:
    """Group name -> bucket snapshot (the /backends robustness pane)."""
    with _group_lock:
        groups = dict(_group_budgets)
    return {g: b.snapshot() for g, b in groups.items()}


def min_retry_tokens():
    """Lowest token count across live budgets; None when no channel
    opted into a budget."""
    vals = [b.tokens() for b in list(_budgets)]
    return round(min(vals), 2) if vals else None


def _ensure_tokens_var() -> None:
    """Expose the retry_tokens_min gauge once the first budget exists
    (a process with no budgets should not dump a meaningless -1)."""
    global _tokens_var_exposed
    if _tokens_var_exposed:
        return
    _tokens_var_exposed = True
    from brpc_tpu.bvar.reducer import PassiveStatus
    PassiveStatus(lambda: (lambda v: -1.0 if v is None else v)(
        min_retry_tokens())).expose("retry_tokens_min")


_default: RetryPolicy | None = None


def default_retry_policy() -> RetryPolicy:
    global _default
    if _default is None:
        _default = RpcRetryPolicy()
    return _default


def _postfork_reset() -> None:
    """Fork hygiene: a seeded backoff policy's RNG would emit the SAME
    jitter sequence in every forked worker — jitter exists to
    desynchronize; a fresh default re-seeds per process. The budget
    registry drops too: the parent's channel buckets describe traffic
    on sockets the child does not own."""
    global _default, _budgets, _tokens_var_exposed
    global _group_budgets, _group_lock
    _default = None
    _budgets = weakref.WeakSet()
    _tokens_var_exposed = False
    _group_budgets = {}
    _group_lock = threading.Lock()


from brpc_tpu.butil import postfork as _postfork  # noqa: E402
#   (registration ships with the singleton it resets)

_postfork.register("rpc.retry_policy", _postfork_reset)


def resolve(policy) -> RetryPolicy:
    """Accept a RetryPolicy, a bare callable, or None (default)."""
    if policy is None:
        return default_retry_policy()
    if isinstance(policy, RetryPolicy):
        return policy
    if callable(policy):
        wrapped = RetryPolicy()
        wrapped.do_retry = lambda cntl: bool(policy(cntl))  # type: ignore
        return wrapped
    raise TypeError(f"not a retry policy: {policy!r}")
