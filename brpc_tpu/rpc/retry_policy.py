"""Pluggable retry decision (brpc/retry_policy.h).

The reference consults a RetryPolicy in OnVersionedRPCReturned
(controller.cpp:634) for every failed attempt — transport failures AND
server-returned errors — so apps can widen (retry an app-specific
status) or narrow (never retry writes) the default. Default policy
mirrors RpcRetryPolicy::DoRetry (retry_policy.cpp:25): retry errors
that plausibly mean "another server / another moment would succeed",
never client-fatal ones (bad request, auth, deadline).
"""

from __future__ import annotations

import random

from brpc_tpu.rpc import errno_codes as berr


class RetryPolicy:
    """Subclass and override do_retry; return True to retry the attempt
    (the controller carries error_code/error_text of the failure)."""

    def do_retry(self, cntl) -> bool:
        raise NotImplementedError

    def retry_backoff_s(self, cntl) -> float:
        """Seconds to wait before the next attempt (0 = immediate, the
        default — existing latency behavior is unchanged unless a
        policy opts in). ``cntl.current_try`` is the 0-based index of
        the attempt that just failed. The channel clamps the wait to
        the call's remaining deadline budget."""
        return 0.0


class RpcRetryPolicy(RetryPolicy):
    """Default: transport/availability errors retry, semantic errors
    don't."""

    RETRYABLE = frozenset({
        berr.EFAILEDSOCKET,   # connection broke mid-call
        berr.ECLOSE,          # peer closed
        berr.ELOGOFF,         # server stopping: another replica may serve
        berr.ELIMIT,          # concurrency limiter rejected: retry elsewhere
        berr.EOVERCROWDED,    # write buffers full
    })

    def do_retry(self, cntl) -> bool:
        return cntl.error_code in self.RETRYABLE


class RetryBackoffPolicy(RpcRetryPolicy):
    """Exponential backoff **with jitter** between retry attempts (the
    reference's ``retry_backoff`` policy family, retry_policy.h):
    attempt N waits ``base_ms * 2**N``, capped at ``max_ms``, then
    spread by ``jitter`` (a ±fraction — attempt storms from correlated
    failures must not re-synchronize on the retry schedule). The
    channel additionally clamps every wait to the call's remaining
    deadline budget, so opting in can never push a call past its own
    deadline.

    ``rng`` is injectable for deterministic tests (chaos lane);
    ``retryable`` optionally overrides the retry decision (a callable
    ``(cntl)->bool``), defaulting to the standard transport-error set.
    """

    def __init__(self, base_ms: float = 20.0, max_ms: float = 1000.0,
                 jitter: float = 0.5, rng: random.Random | None = None,
                 retryable=None):
        if not 0.0 <= jitter <= 1.0:
            raise ValueError("jitter must be within [0, 1]")
        self.base_ms = float(base_ms)
        self.max_ms = float(max_ms)
        self.jitter = float(jitter)
        self._rng = rng or random.Random()
        self._retryable = retryable

    def do_retry(self, cntl) -> bool:
        if self._retryable is not None:
            return bool(self._retryable(cntl))
        return super().do_retry(cntl)

    def retry_backoff_s(self, cntl) -> float:
        b = min(self.base_ms * (2.0 ** cntl.current_try), self.max_ms)
        if self.jitter:
            # b * [1-jitter, 1+jitter): full spread around the nominal
            b *= 1.0 + self.jitter * (2.0 * self._rng.random() - 1.0)
        return b / 1e3


_default: RetryPolicy | None = None


def default_retry_policy() -> RetryPolicy:
    global _default
    if _default is None:
        _default = RpcRetryPolicy()
    return _default


def _postfork_reset() -> None:
    """Fork hygiene: a seeded backoff policy's RNG would emit the SAME
    jitter sequence in every forked worker — jitter exists to
    desynchronize; a fresh default re-seeds per process."""
    global _default
    _default = None


from brpc_tpu.butil import postfork as _postfork  # noqa: E402
#   (registration ships with the singleton it resets)

_postfork.register("rpc.retry_policy", _postfork_reset)


def resolve(policy) -> RetryPolicy:
    """Accept a RetryPolicy, a bare callable, or None (default)."""
    if policy is None:
        return default_retry_policy()
    if isinstance(policy, RetryPolicy):
        return policy
    if callable(policy):
        wrapped = RetryPolicy()
        wrapped.do_retry = lambda cntl: bool(policy(cntl))  # type: ignore
        return wrapped
    raise TypeError(f"not a retry policy: {policy!r}")
