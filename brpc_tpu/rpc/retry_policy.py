"""Pluggable retry decision (brpc/retry_policy.h).

The reference consults a RetryPolicy in OnVersionedRPCReturned
(controller.cpp:634) for every failed attempt — transport failures AND
server-returned errors — so apps can widen (retry an app-specific
status) or narrow (never retry writes) the default. Default policy
mirrors RpcRetryPolicy::DoRetry (retry_policy.cpp:25): retry errors
that plausibly mean "another server / another moment would succeed",
never client-fatal ones (bad request, auth, deadline).
"""

from __future__ import annotations

from brpc_tpu.rpc import errno_codes as berr


class RetryPolicy:
    """Subclass and override do_retry; return True to retry the attempt
    (the controller carries error_code/error_text of the failure)."""

    def do_retry(self, cntl) -> bool:
        raise NotImplementedError


class RpcRetryPolicy(RetryPolicy):
    """Default: transport/availability errors retry, semantic errors
    don't."""

    RETRYABLE = frozenset({
        berr.EFAILEDSOCKET,   # connection broke mid-call
        berr.ECLOSE,          # peer closed
        berr.ELOGOFF,         # server stopping: another replica may serve
        berr.ELIMIT,          # concurrency limiter rejected: retry elsewhere
        berr.EOVERCROWDED,    # write buffers full
    })

    def do_retry(self, cntl) -> bool:
        return cntl.error_code in self.RETRYABLE


_default: RetryPolicy | None = None


def default_retry_policy() -> RetryPolicy:
    global _default
    if _default is None:
        _default = RpcRetryPolicy()
    return _default


def resolve(policy) -> RetryPolicy:
    """Accept a RetryPolicy, a bare callable, or None (default)."""
    if policy is None:
        return default_retry_policy()
    if isinstance(policy, RetryPolicy):
        return policy
    if callable(policy):
        wrapped = RetryPolicy()
        wrapped.do_retry = lambda cntl: bool(policy(cntl))  # type: ignore
        return wrapped
    raise TypeError(f"not a retry policy: {policy!r}")
