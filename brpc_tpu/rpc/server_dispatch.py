"""Server-side request processing (ProcessRpcRequest,
policy/baidu_rpc_protocol.cpp:314 -> user service -> SendRpcResponse :139).

Runs inside the fiber the InputMessenger dispatched; the user handler may
be async (awaited in place) or sync.
"""

from __future__ import annotations

import inspect
import os
import time
from typing import Optional

from brpc_tpu.butil.flags import flag
from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.bvar.reducer import Adder
from brpc_tpu.fiber.keys import FiberLocal
from brpc_tpu.fiber.scheduler import SchedAwaitable, current_group
from brpc_tpu.protocol.proto import tpu_rpc_meta_pb2 as pb
from brpc_tpu.protocol.tpu_std import (
    SMALL_FRAME_MAX, RpcMessage, TpuStdProtocol, pack_message,
    pack_small_frame, serialize_payload, unpack_inline_device_arrays)
from brpc_tpu.rpc import errno_codes as berr
from brpc_tpu.rpc.controller import Controller


_UNSET = object()
_cap = None   # lazily bound brpc_tpu.traffic.capture (one-time import)


def capture_active() -> bool:
    """Whether the traffic recorder wants requests — the gate the
    all-C serving lanes (serve_drain / serve_scan / cut-through) check:
    those never cross the interpreter per request, so they cannot
    capture and must stand down while recording is on. The Python
    lanes (classic AND turbo) capture in-line instead of standing
    down. Covers the legacy rpc_dump_dir flag alias."""
    global _cap
    if _cap is None:
        from brpc_tpu.traffic import capture as _cap
    return _cap.global_recorder().capture_enabled()

# requests shed with ERPCTIMEDOUT because their client budget was gone
# before handler entry (the tail-at-scale lever: a pod under load must
# not burn cycles on requests whose callers gave up) — /vars
nshed = Adder().expose("server_deadline_shed")

# requests shed with ELIMIT by the overload-control gates — the
# concurrency limiter's admission reject and the queue-delay gate
# below (DAGOR-style: shed early, shed cheaply) — /vars
nlimit_shed = Adder().expose("server_limit_shed")

# requests shed with EPRIORITYSHED by the two-level priority-admission
# threshold (rpc/admission.py): below-threshold work rejected at the
# door while the server is in overload — /vars
npriority_shed = Adder().expose("server_priority_shed")


def _queue_delay_shed(server, arrival_ns: int, level: int = 0,
                      level_counted: bool = False) -> bool:
    """True = this request sat in the dispatch queue past the server's
    queue-delay budget and must be shed NOW (before parse/handler):
    a saturated node rejecting in microseconds beats every caller
    timing out in seconds. Counts from the frame's cut-time stamp —
    the same arrival authority as the deadline gates. A trip is an
    overload signal for the priority-admission controller: the NEXT
    below-threshold request sheds by class instead of by age
    (``level_counted`` = admit_level already tallied this request)."""
    qns = server._queue_shed_ns
    if not qns or not arrival_ns:
        return False
    if time.monotonic_ns() - arrival_ns <= qns:
        return False
    nlimit_shed.add(1)
    adm = server._admission
    if adm is not None:
        adm.signal_overload(level, level_counted)
    return True


def _request_level(priority: int, auth_token: str, socket) -> int:
    """Compose the request's admission level: business priority from
    the wire tag, user sub-priority from the caller's cookie (auth
    token) when present, else the connection identity (the server's
    remote_endpoint IS the client socket's local_endpoint — the
    shared admission.cached_socket_slot keeps both sides' hash in
    lockstep)."""
    from brpc_tpu.rpc.admission import (cached_socket_slot, compose_level,
                                        user_slot)
    slot = user_slot(auth_token) if auth_token \
        else cached_socket_slot(socket, socket.remote_endpoint)
    return compose_level(priority, slot)

# the controller of the request THIS fiber is currently serving —
# nested Channel.call inside a handler reads it to inherit the parent's
# remaining deadline budget (min(own timeout, parent remaining)). Set
# around handler invocation only, cleared in finally: input fibers
# serve many requests over their life and a stale context would clamp
# an unrelated later call.
_serving_cntl = FiberLocal()


def current_serving_controller() -> Optional[Controller]:
    """The server-side Controller whose handler is running on this
    fiber/thread, or None outside a handler. Not propagated into
    ``usercode_in_pthread`` pool threads (those handlers see None)."""
    return _serving_cntl.get()


class _NullSpan:
    """Span stand-in when rpcz is off: field writes are absorbed so the
    dispatch path stays branch-free (the reference skips span creation
    the same way when rpcz is disabled, span.cpp:149)."""

    __slots__ = ()

    def __setattr__(self, name, value):
        pass


_NULL_SPAN = _NullSpan()


def _null_finish_span(span, cntl) -> None:
    pass


class _HopToWorker(SchedAwaitable):
    """Move the current fiber from an inline (non-worker) context onto
    a fiber worker before running potentially-blocking user code."""

    def _register(self, fiber):
        fiber.control.schedule(fiber, None)


def _track_pending(socket) -> bool:
    """Whether this socket maintains the pending_responses gate at all:
    only sockets serving a native-echo-capable server can ever enter
    cut-through, so everyone else skips the per-request lock pair."""
    t = socket.__dict__.get("_tracks_pending")
    if t is None:
        server = socket.user_data.get("server")
        t = server is not None and server._native_echo is not None
        socket._tracks_pending = t
    return t


def _settle_pending(socket) -> None:
    with socket.pending_lock:
        if socket.pending_responses > 0:
            socket.pending_responses -= 1


# Claim ownership (the cut-through gate's correctness contract): each
# +1 on socket.pending_responses has exactly ONE owner with a
# try/finally settle —
#   * counted_spawn's wrapper (queue-time claim for every spawned
#     message, held until its coroutine completes), and
#   * process_request_fast's claim for turbo-driven requests, settled
#     by _drive_fast's finally (a suspended turbo handler lets the
#     input loop continue scanning, so the claim must outlive it).
# In-place classic processing needs NO claim: _input_async_tail awaits
# it before the input cycle continues, so nothing can interleave.
async def process_request(proto, msg: RpcMessage, socket) -> None:
    server = socket.user_data.get("server")
    meta = msg.meta
    cid = meta.correlation_id
    if server is None:
        _send_error(proto, socket, cid, berr.EINTERNAL, "no server bound to socket")
        return
    req_meta = meta.request
    # auth precedes lookup: unauthenticated peers must not be able to
    # enumerate the service/method namespace from distinct error codes.
    # verify once per connection, cache the AuthContext on the socket
    # (authenticator.h: only the first message carries/verifies auth).
    # The resolved Authenticator is cached on the server — per-request
    # resolution sat on the hot path for no benefit (the reference
    # resolves once at Server::Start)
    auth = getattr(server, "_resolved_auth_cache", _UNSET)
    if auth is _UNSET:
        from brpc_tpu.rpc.auth import resolve_server_auth
        auth = resolve_server_auth(server.options)
        server._resolved_auth_cache = auth
    auth_ctx = socket.user_data.get("auth_context")
    if auth is not None and auth_ctx is None:
        from brpc_tpu.rpc.auth import AuthError
        try:
            auth_ctx = auth.verify_credential(req_meta.auth_token,
                                              socket.remote_endpoint)
        except AuthError as e:
            _send_error(proto, socket, cid, berr.ERPCAUTH,
                        str(e) or "authentication failed")
            return
        except Exception:
            _send_error(proto, socket, cid, berr.ERPCAUTH, "authentication failed")
            return
        socket.user_data["auth_context"] = auth_ctx
    method = server.find_method(req_meta.service_name, req_meta.method_name)
    if method is None:
        has_svc = req_meta.service_name in server.services()
        _send_error(proto, socket, cid,
                    berr.ENOMETHOD if has_svc else berr.ENOSERVICE,
                    f"unknown {req_meta.service_name}.{req_meta.method_name}")
        return
    method_key = method.full_name or \
        f"{req_meta.service_name}.{req_meta.method_name}"
    # DAGOR priority admission (before parse/interceptor/handler and
    # before any slot): while the server is in overload, requests whose
    # (business, user) level sits below the adaptive threshold shed
    # with a distinct errno — a µs-cheap reject the client's reject
    # discipline treats as neither breakage nor a retry-token spend
    level = 0
    counted = False
    adm = server._admission
    if adm is not None and adm.threshold_engaged():
        level = _request_level(req_meta.priority, req_meta.auth_token,
                               socket)
        counted = True          # admit_level tallies, pass or shed
        if not adm.admit_level(level):
            npriority_shed.add(1)
            _send_error(proto, socket, cid, berr.EPRIORITYSHED,
                        "priority below admission threshold "
                        "(server overloaded)")
            return
    if _queue_delay_shed(server, getattr(msg, "arrival_ns", 0), level,
                         counted):
        # overload: this request aged past the queue-delay budget before
        # dispatch even saw it — reject before parse, interceptor,
        # handler and before taking a concurrency slot
        _send_error(proto, socket, cid, berr.ELIMIT,
                    "queue delay over shed budget (server overloaded)")
        return
    cost = server.on_request_start(
        method_key, msg.payload.size + msg.attachment.size, level,
        counted)
    if not cost:
        _send_error(proto, socket, cid, berr.ELIMIT, "max_concurrency reached")
        return

    t0 = time.monotonic_ns()
    cntl = Controller()
    d = cntl.__dict__
    # serving context for the WHOLE request residence (parse, shed
    # gates, interceptor, handler, response serialize/write): nested
    # Channel.call reads it for deadline/trace inheritance, and the
    # flight recorder's sampler reads it to attribute this fiber's
    # samples to the method. Cleared in the outermost finally — input
    # fibers serve many requests and a stale context would clamp an
    # unrelated later call.
    _serving_cntl.set(cntl)
    try:
        await _process_request_body(proto, msg, socket, server, method,
                                    method_key, cntl, d, t0, cost)
    finally:
        _serving_cntl.set(None)


async def _process_request_body(proto, msg: RpcMessage, socket, server,
                                method, method_key: str, cntl: Controller,
                                d: dict, t0: int,
                                cost: float = 1.0) -> None:
    meta = msg.meta
    cid = meta.correlation_id
    req_meta = meta.request
    auth_ctx = socket.user_data.get("auth_context")
    # deadline propagation: the wire's timeout_ms is the client's whole
    # budget; it counts from the message's cut-time stamp so dispatch
    # queueing (spawned fibers behind busy workers) spends it. The
    # native lanes DEFER timeout-carrying requests to this path
    # (fastcore.cc walk_request_meta), so this stamp-and-shed is the
    # single server-side deadline authority.
    budget_ms = req_meta.timeout_ms
    if budget_ms > 0:
        d["_deadline_ns"] = (getattr(msg, "arrival_ns", 0) or t0) \
            + budget_ms * 1_000_000
    # zero/empty proto3 defaults match the Controller's class defaults:
    # write only what's actually set (instance-dict writes add up here)
    if meta.trace_id:
        d["trace_id"] = meta.trace_id
    if meta.span_id:
        d["span_id"] = meta.span_id
    if req_meta.log_id:
        d["log_id"] = req_meta.log_id
    if req_meta.priority:
        d["request_priority"] = req_meta.priority
    d["remote_side"] = socket.remote_endpoint
    d["local_side"] = socket.local_endpoint
    if req_meta.auth_token:
        d["auth_token"] = req_meta.auth_token
    if auth_ctx is not None:
        d["auth_context"] = auth_ctx
    d["_service_name"] = req_meta.service_name
    d["_method_name"] = req_meta.method_name
    d["_server_socket"] = socket
    # connection-affinity hint for the flight recorder: transport-side
    # samples (the dispatcher draining this conn's bytes) attribute to
    # the method the conn last served — one attr store per request
    socket.last_method = method_key
    rz = flag("rpcz_enabled")
    if rz:
        from brpc_tpu.rpc.span import finish_span, start_server_span
        span = start_server_span(cntl, req_meta.service_name,
                                 req_meta.method_name)
        # the flight recorder's stall watchdog reaches the ACTIVE span
        # through the serving controller (thread -> fiber -> cntl ->
        # span) to annotate an event-thread monopolization in place
        d["_span"] = span
        span.request_size = msg.payload.size + msg.attachment.size
        # timeline base: the frame's cut-time stamp — latency_us then
        # measures full server residence (arrival -> response flushed),
        # and the received->dispatch gap IS the dispatch queueing a
        # flat start/end span could never show (span.h received_us)
        arrival_us = (getattr(msg, "arrival_ns", 0) or t0) // 1000
        span.received_us = arrival_us
        span.start_us = arrival_us
        span.dispatch_us = t0 // 1000
    else:
        span = _NULL_SPAN
        finish_span = _null_finish_span
    if budget_ms > 0 and time.monotonic_ns() >= d["_deadline_ns"]:
        # the client's budget was spent before this request reached
        # dispatch (queued behind busy workers / a pipelined burst):
        # shed it NOW — before parse, interceptor and handler — instead
        # of computing a response nobody is waiting for (Dean & Barroso,
        # The Tail at Scale: expired work amplifies the tail)
        nshed.add(1)
        server.on_request_end(method_key, 0, failed=True, cost=cost)
        cntl.set_failed(berr.ERPCTIMEDOUT,
                        f"deadline {budget_ms}ms expired before dispatch")
        _send_error(proto, socket, cid, berr.ERPCTIMEDOUT,
                    f"deadline {budget_ms}ms expired before dispatch")
        finish_span(span, cntl)   # shed load must show in /rpcz
        cntl.flush_session_kv()
        return
    peer_stream = meta.stream_settings.stream_id   # absent -> 0
    if peer_stream:
        cntl._peer_stream_id = peer_stream
    cntl.request_attachment = msg.attachment
    if meta.device_payloads:
        inline = unpack_inline_device_arrays(msg)
        lane_iter = iter(msg.device_arrays)
        cntl.request_device_arrays = [
            inl if dp.inline_bytes else next(lane_iter, None)
            for dp, inl in zip(meta.device_payloads, inline)]
        dr = getattr(msg, "device_recv", None)
        if rz and dr is not None:
            # the request's device-recv leg as a child of this server
            # span — the receiving half of the sender's stage-resolved
            # device span (shared helper; the client-side twin lives in
            # client_dispatch._fill_response)
            from brpc_tpu.rpc.span import submit_device_recv_span
            submit_device_recv_span(span, dr)

    # decode request payload
    request = None
    cap_rec = None
    try:
        payload_bytes = msg.payload.to_bytes()
        if meta.compress_type:
            from brpc_tpu.rpc.compress import decompress
            payload_bytes = decompress(payload_bytes, meta.compress_type)
            cntl.compress_type = meta.compress_type  # reply in kind
        # capture AFTER decompression so replay re-issues plaintext.
        # Observability must never fail serving: a broken capture dir
        # (perms, disk full) is swallowed here, not turned into
        # EREQUEST. The record completes below with status + latency;
        # a request shed BEFORE this point (deadline/queue gates) is
        # dropped at the door and deliberately not recorded.
        try:
            global _cap
            if _cap is None:
                from brpc_tpu.traffic import capture as _cap
            rec = _cap.global_recorder()
            if rec.capture_enabled():
                # service/method ride as "" — the corpus writer splits
                # the key once per method, so this path never pays the
                # per-request pb string reads
                cap_rec = rec.sample_request(
                    method_key, "", "", payload_bytes, msg.attachment,
                    getattr(msg, "arrival_ns", 0) or t0,
                    req_meta.timeout_ms, req_meta.log_id,
                    req_meta.priority)
        except Exception:
            cap_rec = None
        if method.request_class is not None:
            request = method.request_class()
            request.ParseFromString(payload_bytes)
        else:
            request = payload_bytes
    except Exception as e:
        server.on_request_end(method_key, 0, failed=True, cost=cost)
        cntl.set_failed(berr.EREQUEST, f"cannot parse request: {e}")
        _send_error(proto, socket, cid, berr.EREQUEST, f"cannot parse request: {e}")
        finish_span(span, cntl)  # malformed traffic must show in /rpcz
        if cap_rec is not None:   # malformed is a capture verdict too
            _cap.global_recorder().record_complete(
                cap_rec, berr.EREQUEST,
                (time.monotonic_ns() - t0) / 1e3)
        cntl.flush_session_kv()
        return
    if rz:
        span.parse_done_us = time.monotonic_ns() // 1000

    # interceptor gate (interceptor.h Accept): runs with the decoded
    # request visible on cntl, before the user handler
    interceptor = getattr(server.options, "interceptor", None)
    if interceptor is not None:
        from brpc_tpu.rpc.auth import InterceptorError
        try:
            verdict = interceptor(cntl)
        except InterceptorError as e:
            verdict = (e.error_code, e.reason)
        except Exception as e:
            verdict = (berr.EINTERNAL, f"interceptor error: {e}")
        if verdict is not None:
            code, reason = verdict
            latency_us = (time.monotonic_ns() - t0) / 1e3
            server.on_request_end(method_key, latency_us, failed=True,
                                  cost=cost)
            if cap_rec is not None:   # rejected sessions are corpus too
                _cap.global_recorder().record_complete(cap_rec, code,
                                                   latency_us)
            cntl.set_failed(code, reason)
            _send_error(proto, socket, cid, code, reason)
            finish_span(span, cntl)
            # rejected sessions are the ones operators grep for most:
            # interceptor annotations must still flush (the reference
            # flushes at controller destruction, covering every outcome)
            cntl.flush_session_kv()
            return

    pool = getattr(server, "session_local_pool", None)
    if pool is not None:
        cntl._session_local = pool.borrow()
    response = None
    # (the serving context was installed by process_request for the
    # whole request residence; nested Channel.call inherits through it)
    try:
        if not method.is_coroutine and current_group() is None and \
                not getattr(server.options, "usercode_in_pthread", False):
            # this request is being processed INLINE on a non-worker
            # thread (the event-raising context — socket_inline_process).
            # A sync handler may block, and blocking the caller/dispatcher
            # thread would hijack async call() and stall every other
            # connection — hop to a fiber worker first (the reference
            # never runs user code on the event thread either; its
            # in-place processing happens inside a worker bthread).
            # Async handlers stay inline: suspension converts them to a
            # normal fiber at their first real await.
            await _HopToWorker()
        r = None
        if budget_ms > 0 and time.monotonic_ns() >= d["_deadline_ns"]:
            # the hop parked this request behind busy workers long
            # enough to spend the client's whole budget: shed at the
            # last gate before handler entry (the entry-time shed above
            # catches fan-out queueing; this one catches worker-queue
            # delay)
            nshed.add(1)
            cntl.set_failed(berr.ERPCTIMEDOUT,
                            f"deadline {budget_ms}ms expired before "
                            "handler entry")
        elif _queue_delay_shed(server, getattr(msg, "arrival_ns", 0)):
            # the hop parked this request behind busy workers past the
            # queue-delay budget: the last gate before handler entry
            # (the entry-time gate catches fan-out queueing; this one
            # catches worker-queue delay)
            cntl.set_failed(berr.ELIMIT,
                            "queue delay over shed budget before "
                            "handler entry (server overloaded)")
        else:
            if rz:
                span.handler_start_us = time.monotonic_ns() // 1000
            if getattr(server.options, "usercode_in_pthread", False) and \
                    not method.is_coroutine:
                # blocking user code runs on the backup pthread pool;
                # this fiber (and its worker) stays free to pump IO
                from brpc_tpu.rpc.usercode import run_usercode
                r = await run_usercode(method.handler, cntl, request)
            else:
                r = method.handler(cntl, request)
        if inspect.isawaitable(r):
            r = await r
        response = r
    except Exception as e:
        cntl.set_failed(berr.EINTERNAL, f"{type(e).__name__}: {e}")
    finally:
        # handler exit stamp covers the exception path too (a span whose
        # handler raised still shows where the time went)
        if rz and span.handler_start_us and not span.handler_end_us:
            span.handler_end_us = time.monotonic_ns() // 1000
        if pool is not None:
            pool.give_back(cntl._session_local)
            cntl._session_local = None

    latency_us = (time.monotonic_ns() - t0) / 1e3
    server.on_request_end(method_key, latency_us, failed=cntl.failed(),
                          cost=cost)
    if cap_rec is not None:
        # the record carries its verdict: status + latency ride to disk
        # on the recorder's writer thread, never this dispatch fiber
        _cap.global_recorder().record_complete(cap_rec, cntl.error_code,
                                           latency_us)
    # drop cancel subscriptions BEFORE the response leaves: the peer may
    # read the response and close faster than this context runs its
    # post-write cleanup, and a finished request must not hear about
    # that close (notify_on_cancel exists to stop RUNNING work)
    cntl._drop_cancel_subs()
    try:
        _send_response(proto, socket, cid, cntl, response,
                       span=span if rz else None)
    finally:
        # finish in the finally: a response write that throws (peer
        # already gone) must still land the span in /rpcz — the error
        # sessions are exactly the ones operators grep for. With the
        # flush latch armed, submission waits for the write's on_done.
        finish_span(span, cntl)
        # kvmap.h: one greppable line per session — even when the
        # response write throws
        cntl.flush_session_kv()


def _synth_request_msg(cid: int, service: str, method_name: str,
                       log_id: int, payload: bytes, att: bytes,
                       arrival_ns: int = 0) -> RpcMessage:
    """Rebuild a classic RpcMessage from scan_frames fields (the rare
    turbo->classic fallback: unknown method, configured auth, rpcz on).
    ``arrival_ns`` carries the scan lane's cut-time stamp forward so the
    deadline budget and the span's received_us anchor at the real frame
    cut, not at this re-synthesis."""
    meta = pb.RpcMeta()
    meta.correlation_id = cid
    meta.request.service_name = service
    meta.request.method_name = method_name
    if log_id:
        meta.request.log_id = log_id
    meta.attachment_size = len(att)
    p = IOBuf()
    if payload:
        p.append(payload)
    a = IOBuf()
    if att:
        a.append(att)
    msg = RpcMessage(meta, p, a)
    if arrival_ns:
        msg.arrival_ns = arrival_ns
    return msg


def make_fast_drain(server):
    """Build the native per-event serving hook (Socket.fast_drain): ONE
    fastcore serve_drain call reads the readable fd and echo-serves its
    front run — recv, frame cut, meta walk, dispatch match and response
    build never cross the interpreter (the reference's compiled drain +
    in-place serve, socket.cpp:2402 DoRead + input_messenger.cpp:219 +
    baidu_rpc_protocol.cpp:314). Anything the C pass can't judge is
    re-injected into the portal for the classic machinery. Returns None
    when the extension is unavailable."""
    from brpc_tpu.native import fastcore as _fc_loader
    fc = _fc_loader.get()
    sd = getattr(fc, "serve_drain", None) if fc is not None else None
    ss = getattr(fc, "serve_scan", None) if fc is not None else None
    if sd is None or ss is None:
        return None
    from brpc_tpu.protocol.tpu_std import MAGIC
    from brpc_tpu.transport.socket import nreads as _nreads
    from brpc_tpu.transport.socket import pull_chunks as _pull_chunks

    def _defer_streak(sock, served: bool) -> None:
        """Disable the lane for a connection that keeps deferring: a
        tpu_std client that never hits the native-echo method would
        otherwise pay the recv-copy-reinject detour on every event,
        forever. Any served frame resets the streak."""
        if served:
            sock.__dict__["_fdrain_defer_streak"] = 0
            return
        streak = sock.__dict__.get("_fdrain_defer_streak", 0) + 1
        if streak >= 16:
            sock.fast_drain = None
        else:
            sock._fdrain_defer_streak = streak

    def fast_drain(sock) -> bool:
        tgt = server._native_echo
        adm = server._admission
        if tgt is None or not _server_turbo_ok(server) \
                or flag("rpcz_enabled") or capture_active() \
                or (adm is not None and adm.threshold_engaged()) \
                or sock.input_portal or sock.input_need \
                or sock.user_data.get("_cut_forward") is not None:
            # (the admission clause: the all-C echo loop serves without
            # crossing the interpreter, so it can neither judge levels
            # nor piggyback the threshold — while the server is
            # shedding by priority it stands down, like capture)
            return False
        pfd = getattr(sock.conn, "pluck_fd", None)
        if pfd is not None:
            # the pinned dup (Socket.pin_fd_acquire) pins the kernel
            # socket against fd-number recycling mid-recv, amortized
            # over the connection instead of a dup+close per event
            dfd = sock.pin_fd_acquire()
            if dfd < 0:
                return False
            t0 = time.monotonic_ns()
            try:
                r = sd(dfd, MAGIC, tgt[0], tgt[1], SMALL_FRAME_MAX)
            finally:
                sock.pin_fd_release()
            tag = r[0]
            nr = r[-1]            # bytes the C loop read this call
            if nr:
                _nreads.add(nr)   # classic _drain_readable's accounting
            if tag == 0:
                _, out, n, leftover, _nr = r
                sock.write_small(out)
                server.account_native_batch(
                    tgt[2], n, (time.monotonic_ns() - t0) / 1e3)
                _defer_streak(sock, True)
                if leftover:
                    # non-echo tail (pipelined slow frame / partial):
                    # the classic pass judges it with full semantics
                    sock.input_portal.append_user_data(leftover)
                    return False
                return True
            if tag == 1:
                leftover = r[1]
                if leftover:
                    if not MAGIC.startswith(leftover[:4]):
                        # the portal was empty, so these bytes sit at a
                        # frame boundary — a magic mismatch means this
                        # connection speaks another protocol (HTTP,
                        # redis, ...): stop paying the native recv
                        # detour on its every readable event
                        sock.fast_drain = None
                    else:
                        _defer_streak(sock, False)
                    sock.input_portal.append_user_data(leftover)
                    return False
                return True           # spurious wake: nothing arrived
            # tag == 2: EOF/error. With buffered bytes the classic pass
            # processes them first and its next drain re-observes the
            # sticky EOF/error state; with none, fail now (the classic
            # drain's "peer closed" verdict, Socket._drain_readable)
            if r[2]:
                sock.input_portal.append_user_data(r[2])
                return False
            sock.set_failed(ConnectionResetError(r[1]))
            return True
        # chunk-handoff transports (mem://): the writer's exact bytes
        # objects are the stream — serve straight off them, skipping
        # the portal wrap/view/pop round trip entirely
        data, handled = _pull_chunks(sock)
        if data is None:
            return handled
        t0 = time.monotonic_ns()
        consumed, out, n = ss(data, MAGIC, tgt[0], tgt[1], SMALL_FRAME_MAX)
        if n:
            sock.write_small(out)
            server.account_native_batch(
                tgt[2], n, (time.monotonic_ns() - t0) / 1e3)
        if consumed < len(data):
            rest = data[consumed:] if consumed else data
            if not n and not MAGIC.startswith(rest[:4]):
                sock.fast_drain = None    # another protocol: stop here
            else:
                _defer_streak(sock, bool(n))
            sock.input_portal.append_user_data(rest)
            return False
        _defer_streak(sock, bool(n))
        return True

    return fast_drain


def _server_turbo_ok(server) -> bool:
    """Feature gate for the turbo request path, resolved once: servers
    with auth / interceptor / session pools / pthread usercode need the
    classic path's full semantics."""
    ok = server.__dict__.get("_turbo_ok")
    if ok is None:
        from brpc_tpu.rpc.auth import resolve_server_auth
        o = server.options
        ok = (resolve_server_auth(o) is None
              and getattr(o, "interceptor", None) is None
              and getattr(server, "session_local_pool", None) is None
              and not getattr(o, "usercode_in_pthread", False))
        server._turbo_ok = ok
    return ok


async def _drive_fast(proto, socket, server, method, method_key: str,
                      cid: int, service: str, method_name: str,
                      log_id: int, payload: bytes, att: bytes,
                      arrival_ns: int = 0, cost: float = 1.0) -> None:
    """The turbo request body: Controller setup, handler, response —
    the classic process_request minus every branch the scan_frames
    eligibility rules already guarantee can't apply (no auth, no
    interceptor, no compression, no streams, no device payloads, rpcz
    off). Driven by ONE coro.send(None) from process_request_fast, so
    a synchronously-completing handler touches no Fiber at all."""
    if not _track_pending(socket):
        await _drive_fast_inner(proto, socket, server, method, method_key,
                                cid, service, method_name, log_id, payload,
                                att, arrival_ns, cost)
        return
    try:
        await _drive_fast_inner(proto, socket, server, method, method_key,
                                cid, service, method_name, log_id, payload,
                                att, arrival_ns, cost)
    finally:
        # THE single settle of process_request_fast's claim — exactly
        # once, on success and on every escape path alike
        _settle_pending(socket)


async def _drive_fast_inner(proto, socket, server, method, method_key: str,
                            cid: int, service: str, method_name: str,
                            log_id: int, payload: bytes, att: bytes,
                            arrival_ns: int = 0,
                            cost: float = 1.0) -> None:
    t0 = time.monotonic_ns()
    cntl = Controller()
    d = cntl.__dict__
    if log_id:
        d["log_id"] = log_id
    d["remote_side"] = socket.remote_endpoint
    d["local_side"] = socket.local_endpoint
    d["_service_name"] = service
    d["_method_name"] = method_name
    d["_server_socket"] = socket
    if att:
        ab = IOBuf()
        ab.append(att)
        d["request_attachment"] = ab
    # traffic capture, turbo flavor: the scan lane only admits metas
    # with no timeout/priority/auth (the C walker defers the rest to
    # the classic path), so those fields are 0 by construction here.
    # payload/att are already bytes — the sampled path costs one
    # sampling decision + one slots-object allocation.
    cap_rec = None
    if capture_active():
        try:
            cap_rec = _cap.global_recorder().sample_request(
                method_key, "", "", payload, att,
                arrival_ns or t0, 0.0, log_id, 0)
        except Exception:
            cap_rec = None
    request: object = payload
    if method.request_class is not None:
        try:
            request = method.request_class()
            request.ParseFromString(payload)
        except Exception as e:
            server.on_request_end(method_key, 0, failed=True, cost=cost)
            if cap_rec is not None:
                _cap.global_recorder().record_complete(
                    cap_rec, berr.EREQUEST,
                    (time.monotonic_ns() - t0) / 1e3)
            _send_error(proto, socket, cid, berr.EREQUEST,
                        f"cannot parse request: {e}")
            return
    response = None
    try:
        if not method.is_coroutine and current_group() is None:
            # blocking user code must not run on the event thread
            # (same rule as the classic path)
            await _HopToWorker()
        if _queue_delay_shed(server, arrival_ns):
            # the turbo lane's post-hop queue-delay gate (mirrors the
            # classic path): this request aged behind busy workers
            # past the shed budget — reject before the handler runs
            server.on_request_end(method_key, 0, failed=True, cost=cost)
            if cap_rec is not None:
                _cap.global_recorder().record_complete(
                    cap_rec, berr.ELIMIT,
                    (time.monotonic_ns() - t0) / 1e3)
            cntl._drop_cancel_subs()
            _send_error(proto, socket, cid, berr.ELIMIT,
                        "queue delay over shed budget before handler "
                        "entry (server overloaded)")
            return
        r = method.handler(cntl, request)
        if inspect.isawaitable(r):
            r = await r
        response = r
    except Exception as e:
        cntl.set_failed(berr.EINTERNAL, f"{type(e).__name__}: {e}")
    latency_us = (time.monotonic_ns() - t0) / 1e3
    server.on_request_end(method_key, latency_us, failed=cntl.failed(),
                          cost=cost)
    if cap_rec is not None:
        _cap.global_recorder().record_complete(cap_rec, cntl.error_code,
                                           latency_us)
    # before the send: see process_request's twin comment (the peer can
    # close faster than post-write cleanup runs)
    cntl._drop_cancel_subs()
    try:
        # _send_response's own small-frame fast path covers the
        # plain-bytes success shape; one sender, one eligibility ladder
        _send_response(proto, socket, cid, cntl, response)
    finally:
        cntl.flush_session_kv()


def process_request_fast(proto, socket, server, cid: int, service: str,
                         method_name: str, log_id: int, payload: bytes,
                         att: bytes, is_last: bool = True,
                         arrival_ns: int = 0):
    """Dispatch one scan_frames request record. Returns None when fully
    handled (inline completion or adopted continuation), or a classic
    process_request coroutine for the caller to run (fallback cases).

    This is the Python half of the native per-call loop: scan_frames
    already cut the frame and decoded the meta in C; what remains here
    is the method lookup, the handler, and the (native) response pack —
    the reference runs the same span compiled
    (baidu_rpc_protocol.cpp:314 ProcessRpcRequest)."""
    if server is None or not _server_turbo_ok(server) or \
            flag("rpcz_enabled"):
        # NOTE: capture no longer bounces this lane to the classic
        # path — the turbo body records in-line (_drive_fast_inner),
        # so the hot lane keeps serving while the recorder runs
        return process_request(
            proto, _synth_request_msg(cid, service, method_name, log_id,
                                      payload, att, arrival_ns), socket)
    method = server.find_method(service, method_name)
    if method is None:
        # error responses here run synchronously in the input context:
        # nothing can interleave, no claim needed
        has_svc = service in server.services()
        _send_error(proto, socket, cid,
                    berr.ENOMETHOD if has_svc else berr.ENOSERVICE,
                    f"unknown {service}.{method_name}")
        return None
    method_key = method.full_name or f"{service}.{method_name}"
    # priority admission, turbo flavor: scan-lane requests carry no
    # priority/auth BY CONSTRUCTION (the C walker defers those metas
    # to the classic lane), so the level is business class 0 + the
    # connection's user slot — below-threshold conns shed here exactly
    # like the classic path (the gate discipline must not depend on
    # which dispatch lane a burst landed in)
    level = 0
    counted = False
    adm = server._admission
    if adm is not None and adm.threshold_engaged():
        level = _request_level(0, "", socket)
        counted = True          # admit_level tallies, pass or shed
        if not adm.admit_level(level):
            npriority_shed.add(1)
            _send_error(proto, socket, cid, berr.EPRIORITYSHED,
                        "priority below admission threshold "
                        "(server overloaded)")
            return None
    if _queue_delay_shed(server, arrival_ns, level, counted):
        # the turbo lane sheds through the same queue-delay gate as the
        # classic path
        _send_error(proto, socket, cid, berr.ELIMIT,
                    "queue delay over shed budget (server overloaded)")
        return None
    cost = server.on_request_start(method_key, len(payload) + len(att),
                                   level, counted)
    if not cost:
        _send_error(proto, socket, cid, berr.ELIMIT,
                    "max_concurrency reached")
        return None
    socket.last_method = method_key   # flight-recorder affinity hint
    if _track_pending(socket):
        # claimed HERE (before the handler can suspend and let the
        # input loop continue); _drive_fast's finally settles it
        with socket.pending_lock:
            socket.pending_responses += 1
    # the fiber is NAMED with the method key: the flight recorder's
    # sampler attributes a turbo-lane sample to its RPC method through
    # the fiber name alone — the slim path never pays a fiber-local set
    coro = _drive_fast(proto, socket, server, method, method_key, cid,
                       service, method_name, log_id, payload, att,
                       arrival_ns, cost)
    if not method.is_coroutine and not is_last:
        # the classic loop's fan-out discipline (QueueMessage,
        # input_messenger.cpp:183): a blocking handler for a non-last
        # burst message gets a fresh fiber, so it can't serialize the
        # burst behind it (async handlers stay inline — suspension is
        # their fan-out)
        socket._control.spawn(coro, name=method_key)
    else:
        # run_inline gives the first leg full fiber context
        # (_tls.current for fiber-locals) and owns the depth cap /
        # suspension parking
        socket._control.run_inline(coro, name=method_key)
    return None


def _send_response(proto, socket, cid: int, cntl: Controller,
                   response, span=None) -> None:
    """``span``: a live rpcz Span to stamp the serialize/flush stages
    on. The flushed_us stamp rides the write's completion callback
    (expect_flush/mark_flushed latch), so a blocked response write —
    saturated peer, chaos delay — shows up as write-stage time instead
    of vanishing between dispatch and /rpcz."""
    on_done = None
    if span is not None:
        from brpc_tpu.rpc.span import expect_flush, mark_flushed
        on_done = lambda err, s=span: mark_flushed(s, err)  # noqa: E731
    # DAGOR threshold piggyback: while this server is shedding by
    # priority, the current admission threshold rides EVERY response
    # (success and shed alike) so senders can fail doomed traffic fast
    # at the source. Calm servers (threshold 0) pay two lookups and
    # keep the wire byte-identical — the field stays absent, and
    # responses stay eligible for the client's native scan lane
    # (which defers unknown response-meta fields to the classic parse,
    # exactly when the threshold needs full semantics).
    adm_thr = 0
    srv = socket.user_data.get("server")
    if srv is not None:
        adm = srv._admission
        if adm is not None:
            adm_thr = adm.wire_threshold()
    # small-call fast path: a successful tpu_std-framed response with no
    # stream/device/progressive sections needs only correlation_id (+
    # attachment_size) in its meta — hand-encoded varints over a single
    # bytes frame, no pb object, no IOBuf
    att = cntl.__dict__.get("response_attachment")
    if (not adm_thr and not cntl.failed() and cntl.compress_type == 0
            and getattr(cntl, "_accepted_stream", None) is None
            and not cntl.__dict__.get("response_device_arrays")
            and type(proto).frame is TpuStdProtocol.frame):
        try:
            payload = serialize_payload(response)
        except TypeError as e:
            cntl.set_failed(berr.EINTERNAL, str(e))
        else:
            if len(payload) + (att.size if att else 0) <= SMALL_FRAME_MAX:
                wire = pack_small_frame(b"", cid, payload,
                                        att.to_bytes() if att else b"",
                                        magic=proto.MAGIC)
                if span is not None:
                    span.response_size = len(payload)
                    span.serialized_us = time.monotonic_ns() // 1000
                    expect_flush(span)
                socket.write_small(wire, on_done=on_done)
                return
            # big response: stay zero-copy (IOBuf chain) below
    meta = pb.RpcMeta()
    meta.correlation_id = cid
    meta.response.error_code = cntl.error_code
    meta.response.error_text = cntl.error_text
    if adm_thr:
        meta.response.admission_threshold = adm_thr
    accepted = getattr(cntl, "_accepted_stream", None)
    if accepted is not None:
        meta.stream_settings.stream_id = accepted.id
    payload = b""
    if not cntl.failed():
        try:
            payload = serialize_payload(response)
            if cntl.compress_type and payload:
                from brpc_tpu.rpc.compress import compress
                payload = compress(payload, cntl.compress_type)
                meta.compress_type = cntl.compress_type
        except TypeError as e:
            meta.response.error_code = berr.EINTERNAL
            meta.response.error_text = str(e)
    use_lane = (bool(cntl.response_device_arrays)
                and socket.conn.supports_device_lane)
    att = IOBuf()
    att.append_buf(cntl.response_attachment)
    framer = getattr(proto, "frame", None)
    if framer is not None:
        wire, lane = framer(meta, payload, attachment=att,
                            device_arrays=cntl.response_device_arrays,
                            device_lane=use_lane)
    else:
        wire, lane = pack_message(meta, payload, attachment=att,
                                  device_arrays=cntl.response_device_arrays,
                                  device_lane=use_lane)
    if span is not None:
        span.response_size = len(payload)
        span.serialized_us = time.monotonic_ns() // 1000
    if lane is not None:
        # adjacent pair under the lane lock (see Channel._issue_rpc).
        # The defer-flush hold keeps the TCP syscalls for both frames
        # OUT of the lane_lock critical section (one gather-write at
        # release) — worker fibers were measurably serializing on the
        # flush here under concurrent device-payload responses.
        conn = getattr(socket, "conn", None)
        hold = getattr(conn, "hold_flush", None)
        if hold is not None:
            hold()
        try:
            with socket.lane_lock:
                # the response batch's stage tracker hangs its device span
                # off this request's server span (trace inheritance)
                socket.write_device_payload(lane, span=span)
                if span is not None:
                    # armed only once the write is certain to be issued (an
                    # armed latch with no callback would strand the span)
                    expect_flush(span)
                # graftlint: disable=callback-under-lock -- lane_lock makes
                # the device batch + envelope adjacent on the conn (same
                # pairing discipline as Channel._issue_rpc); Socket.write
                # only queues and on_done fires from the drain
                socket.write(wire, on_done=on_done)
        finally:
            if hold is not None:
                conn.release_flush()
    else:
        if span is not None:
            expect_flush(span)
        socket.write(wire, on_done=on_done)


def _send_error(proto, socket, cid: int, code: int, text: str) -> None:
    cntl = Controller()
    cntl.set_failed(code, text)
    _send_response(proto, socket, cid, cntl, None)
