"""PeriodicTask: run a callback on an interval via the TimerThread
(brpc/periodic_task.{h,cpp} — health-check/naming/trackme style
periodic work without a dedicated thread).

The next run is scheduled AFTER the current one completes (fixed delay,
like the reference — a slow task never stacks up). ``interval_s`` may be
a callable for adaptive intervals. destroy() stops it."""

from __future__ import annotations

import threading
from typing import Callable, Optional, Union

from brpc_tpu.fiber.timer import global_timer


class PeriodicTask:
    def __init__(self, fn: Callable[[], None],
                 interval_s: Union[float, Callable[[], float]],
                 run_immediately: bool = False):
        self._fn = fn
        self._interval = interval_s
        self._lock = threading.Lock()
        self._stopped = False
        self._timer_id: Optional[int] = None
        if run_immediately:
            self._run()
        else:
            self._schedule()

    def _delay(self) -> float:
        return self._interval() if callable(self._interval) \
            else float(self._interval)

    def _schedule(self) -> None:
        with self._lock:
            if self._stopped:
                return
            self._timer_id = global_timer().schedule_after(
                self._delay(), self._run)

    def _run(self) -> None:
        try:
            self._fn()
        except Exception:
            import logging
            logging.getLogger("brpc_tpu.rpc").exception(
                "periodic task failed")
        self._schedule()

    def destroy(self) -> None:
        with self._lock:
            self._stopped = True
            tid, self._timer_id = self._timer_id, None
        if tid is not None:
            global_timer().unschedule(tid)
