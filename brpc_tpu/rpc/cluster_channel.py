"""ClusterChannel: Channel over a naming service + load balancer
(Channel::Init(ns_url, lb_name) + details/load_balancer_with_naming.*).

Per (re)issue: excluded = circuit-breaker-isolated + already-tried (retry
goes elsewhere); the LB picks; completion feeds latency back to the LB and
the breaker. Failed endpoints enter the health checker, which probes them
with backoff and revives them (details/health_check.cpp:59-146).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from brpc_tpu.butil.endpoint import EndPoint
from brpc_tpu.bvar.reducer import Adder
from brpc_tpu.fiber import TaskControl
from brpc_tpu.rpc import backend_stats as _bs
from brpc_tpu.rpc import errno_codes as berr
from brpc_tpu.rpc.channel import Channel, ChannelOptions
from brpc_tpu.rpc.circuit_breaker import ClusterBreakers
from brpc_tpu.rpc.controller import Controller
from brpc_tpu.rpc.health_check import HealthChecker
from brpc_tpu.rpc.load_balancer import LoadBalancer, new_load_balancer
from brpc_tpu.rpc.naming import NamingServiceThread
from brpc_tpu.transport.socket import Socket, create_client_socket

# calls failed fast because the naming service has delivered no
# servers (never resolved, or resolved to an empty list) — /vars
nnaming_empty = Adder().expose("naming_empty")


class NamingEmptyError(ConnectionError):
    """Selection failed because the server list is EMPTY (not because
    every server is excluded): carries its own errno so callers see
    ENAMINGEMPTY instead of a generic EFAILEDSOCKET pick failure —
    a misconfigured naming url fails fast and greppably, it does not
    burn the retry budget against nothing."""

    berrno = berr.ENAMINGEMPTY


class ClusterChannel(Channel):
    def __init__(self, naming_url: str, load_balancer: str | LoadBalancer = "rr",
                 options: Optional[ChannelOptions] = None,
                 control: Optional[TaskControl] = None):
        # telemetry identity: the naming url names the dependency
        # better than an auto "channel-N" — stashed BEFORE super() so
        # the base constructor's one registration uses it
        self._naming_url = naming_url
        super().__init__(address=None, options=options, control=control)
        self._lb = (load_balancer if isinstance(load_balancer, LoadBalancer)
                    else new_load_balancer(load_balancer))
        # resolved once: does this balancer expose decision factors for
        # the trace ring (rr/random/hash return None — skip the call)
        self._lb_has_info = type(self._lb).decision_info \
            is not LoadBalancer.decision_info
        self._breakers = ClusterBreakers()
        self._sockets: Dict[EndPoint, Socket] = {}
        self._sockets_lock = threading.Lock()
        self._servers: list = []
        self._health = HealthChecker(
            control=self._control,
            app_check=self.options.app_health_check,
            on_event=self._on_health_event)
        self._ns = NamingServiceThread(naming_url, control=self._control)
        self._ns.watch(self._on_servers)
        self._ns.wait_first_update(self.options.naming_wait_s)

    # ------------------------------------------------------------- naming
    def _on_servers(self, servers):
        ns_filter = getattr(self.options, "ns_filter", None)
        if ns_filter is not None:
            # naming_service_filter.h Accept(): rejected servers never
            # reach the LB (filtered at list-reset, not at pick time)
            servers = [ep for ep in servers if ns_filter(ep)]
        self._servers = servers
        self._lb.reset_servers(servers)
        self._health.retain(servers)
        _bs.ring_event(self._stats_name, "naming", count=len(servers),
                       servers=_bs._ep_list(servers))

    def servers(self):
        return list(self._servers)

    def _on_health_event(self, event: str, ep) -> None:
        """Health-checker transitions land in the decision ring: a
        'dead' event explains why later selects exclude the backend, a
        'revived' one why it reappears — and tells the balancer to
        reset the endpoint's adaptive state (a node that died with a
        penalty-saturated latency estimate must not return at ~zero
        weight)."""
        if event == "revived":
            try:
                self._lb.revive(ep)
            except Exception:
                pass
        _bs.ring_event(self._stats_name, "health", event=event,
                       endpoint=_bs.ep_key(ep))

    def _bs_ring(self):
        """The channel's decision-ring deque, cached (re-resolved when
        the lb_trace_ring flag moves — the registry rebuilds the deque
        then, and events must land where /lb_trace reads)."""
        from brpc_tpu.butil.flags import flag
        r = self.__dict__.get("_bs_ring_cache")
        if r is None or r.maxlen != flag("lb_trace_ring"):
            r = _bs.global_stats().ring(self._stats_name)
            self.__dict__["_bs_ring_cache"] = r
        return r

    # ------------------------------------------------- telemetry state
    def _default_stats_name(self) -> str:
        return self._naming_url

    @property
    def lb_name(self) -> str:
        return getattr(self._lb, "name", type(self._lb).__name__)

    def naming_info(self) -> dict:
        return {"url": str(getattr(self._ns, "url", "")) or None,
                "servers": len(self._servers),
                "revision": self._ns.revision(),
                "last_update_age_s": self._ns.last_update_age_s()}

    def backend_state(self, key: str) -> dict:
        """Breaker/health/naming state for one /backends row (``key``
        is the canonical backend key). Rows for backends no longer in
        any list report in_naming=False — stale rows are visible, not
        silently dropped."""
        out = {"in_naming": False, "health_dead": False}
        for ep in list(self._servers):
            if _bs.ep_key(ep) == key:
                out["in_naming"] = True
                break
        for ep in self._health.dead_set():
            if _bs.ep_key(ep) == key:
                out["health_dead"] = True
                break
        with self._breakers._lock:
            items = list(self._breakers._breakers.items())
        for ep, b in items:
            if _bs.ep_key(ep) == key:
                out["breaker"] = b.snapshot()
                break
        return out

    # ----------------------------------------------------------- selection
    def _pick_socket(self, cntl: Controller) -> Socket:
        if not self._servers:
            # empty server list is its own failure mode: either the
            # naming service never resolved (revision 0 — bad url,
            # dead registry) or it resolved to nothing. Fail fast with
            # a distinct errno instead of a generic pick failure that
            # looks like N dead backends.
            nnaming_empty.add(1)
            rev = self._ns.revision()
            why = ("never delivered a server list"
                   if rev == 0 else f"delivered an empty list "
                   f"(revision {rev})")
            raise NamingEmptyError(
                f"naming {self._naming_url!r} {why}")
        tried = set(cntl.tried_servers)
        isolated = self._breakers.isolated_set(self._servers)
        dead = self._health.dead_set()
        exclude = tried | isolated | dead
        key = getattr(cntl, "request_key", None)
        ep = self._lb.select_server(exclude or None, request_key=key)
        fallback = False
        if ep is None and exclude:
            # every server excluded: last resort — but staged. Drop the
            # per-call exclusions (tried/breaker) FIRST while still
            # avoiding known-dead backends: a retry-exhausted call that
            # roulettes onto a dead node is a guaranteed failure, and
            # probing the dead is the health checker's job, not a live
            # request's. Only when every backend is dead (full outage)
            # does the probe-anyone gate open.
            fallback = True
            if dead:
                ep = self._lb.select_server(dead, request_key=key)
            if ep is None:
                ep = self._lb.select_server(None, request_key=key)
        if _bs.enabled():
            # the decision ring records WHY: the chosen backend, what
            # was excluded and for which reason, and (for weighted
            # balancers that expose it) the decision factors behind
            # the winner's weight
            info = None
            if ep is not None and self._lb_has_info:
                try:
                    info = self._lb.decision_info(ep)
                except Exception:
                    info = None
            ev = {"endpoint": self._bs_cell(ep)[0] if ep is not None
                  else None,
                  "lb": self.lb_name, "attempt": len(tried) + 1}
            excluded = {}
            if tried:
                excluded["tried"] = _bs._ep_list(tried)
            if isolated:
                excluded["breaker"] = _bs._ep_list(isolated)
            if dead:
                excluded["health"] = _bs._ep_list(dead)
            if excluded:
                ev["excluded"] = excluded
            if fallback:
                ev["fallback"] = True   # every backend excluded: the
                #                         recover gate probed anyway
            if info:
                ev["info"] = info
            _bs.ring_event(self._stats_name, "select",
                           ring=self._bs_ring(), **ev)
        if ep is None:
            raise ConnectionError("no server available")
        # a backup attempt can lose the race with the primary response:
        # once the completion sweep has run (it records, under the same
        # lock, how many tried entries it accounted for), nobody will
        # ever return THIS selection's inflight slot — return it here
        # and abort the attempt instead of leaking it (which would
        # starve la-weighted servers)
        with cntl._lb_lock:
            if cntl._lb_swept_n is not None:
                self._lb.abandon(ep)
                _bs.ring_event(self._stats_name, "abandon",
                               endpoint=_bs.ep_key(ep),
                               why="late attempt after completion")
                raise ConnectionError("call already completed "
                                      "(late backup/retry attempt dropped)")
            cntl.tried_servers.append(ep)
        if self._on_call_complete not in cntl._complete_hooks:
            cntl._add_complete_hook(self._on_call_complete)
        return self._socket_for(ep)

    def _socket_for(self, ep: EndPoint) -> Socket:
        from brpc_tpu.rpc.channel import connect_dedup

        def _make():
            try:
                s = create_client_socket(
                    ep, on_input=self._messenger.on_new_messages,
                    control=self._control)
            except (ConnectionError, OSError):
                # a refused/unreachable CONNECT is the dead-node signal
                # for endpoints that never produced a Socket —
                # established sockets report through on_failed below,
                # but a killed node's fresh connects fail HERE, and
                # without this mark the LB keeps selecting it (each
                # hedged call's retries then burn against a node the
                # health checker was never told about)
                self._health.mark_dead(ep)
                raise
            return self._wire_socket(s, ep)

        def _write(s):
            self._sockets[ep] = s

        return connect_dedup(self._sockets_lock,
                             lambda: self._sockets.get(ep), _write, _make)

    def _wire_socket(self, s: Socket, ep: EndPoint) -> Socket:
        from brpc_tpu.rpc.channel import client_fast_drain_hook
        s.fast_drain = client_fast_drain_hook(self.options)
        s.on_failed(lambda sock, ep=ep: self._on_socket_failed(ep))
        self._label_socket(s, ep)
        return s

    def _on_socket_failed(self, ep: EndPoint):
        self._health.mark_dead(ep)

    # ------------------------------------------------------------ feedback
    def _on_attempt_failed(self, cntl: Controller, code: int, text: str,
                           failed_ep=None):
        """Intermediate retry attempts: the failed server must hear about
        it (else it never isolates while retries keep saving the call).
        Attribution prefers the endpoint the failure path captured — a
        concurrent backup selection can make tried_servers[-1] a
        different (healthy) server."""
        with cntl._lb_lock:
            tried = cntl.tried_servers
            if failed_ep is not None and failed_ep in tried:
                ep = failed_ep
            elif tried:
                ep = tried[-1]
            else:
                ep = None
            if ep is not None:
                # under the SAME hold as the resolve: the completion
                # sweep's fed-snapshot must see this entry or it would
                # abandon a selection whose feedback is being delivered
                cntl._lb_fed.append(ep)
        # backend stat cells + attempt spans (base hook) see the same
        # resolved endpoint the LB/breaker feedback uses
        super()._on_attempt_failed(cntl, code, text, ep)
        if ep is None:
            return
        if code in _bs.REJECT_CODES:
            # overload shed: failure-without-latency — the slot
            # returns, the reject is counted, but neither the LALB
            # latency EWMA (error penalty) nor the circuit breaker
            # hears about it: a node protecting itself by shedding is
            # NOT broken, and isolating it would dogpile the rest
            self._lb.feedback_reject(ep)
        else:
            self._lb.feedback(ep, cntl.latency_us(), True)
            self._breakers.on_call(ep, failed=True)
        if _bs.enabled():
            _bs.ring_event(self._stats_name, "feedback",
                           ring=self._bs_ring(),
                           endpoint=self._bs_cell(ep)[0],
                           failed=("reject" if code in _bs.REJECT_CODES
                                   else True),
                           code=code)

    def _on_call_complete(self, cntl: Controller):
        # the marker and the tried snapshot are taken under the same
        # lock _pick_socket appends under: a late backup attempt either
        # lands before this (and is swept here) or sees the marker and
        # returns its own slot — no in-between
        with cntl._lb_lock:
            cntl._lb_swept_n = len(cntl.tried_servers)
            tried = list(cntl.tried_servers)
            fed_snapshot = list(cntl._lb_fed)
        if not tried:
            return
        # attribute the final observation to the server whose RESPONSE
        # completed the call (with a backup in flight, the last-selected
        # server is often the losing one); timeouts/failures have no
        # responder and fall back to the last attempt
        ep = cntl.responded_server
        if ep is None or ep not in tried:
            ep = tried[-1]
        if cntl.error_code == berr.ECANCELED:
            # cancellation is client-local: no server failed, and the
            # truncated latency is meaningless — abandon every selection
            # (returns inflight slots without polluting stats) instead
            # of feeding the LB/breaker a bogus observation
            for s in tried:
                if s in fed_snapshot:
                    fed_snapshot.remove(s)
                else:
                    self._lb.abandon(s)
                    if _bs.enabled():
                        _bs.ring_event(self._stats_name, "abandon",
                                       endpoint=_bs.ep_key(s),
                                       why="canceled")
            return
        code = cntl.error_code
        if _bs.is_reject(code, cntl.responded_server):
            # the call's VERDICT is an overload shed (ELIMIT, write
            # overcrowding, or a server-responded deadline shed): same
            # reject discipline as the intermediate-attempt path —
            # slot back, no latency sample, breaker untouched
            self._lb.feedback_reject(ep)
            if _bs.enabled():
                _bs.ring_event(self._stats_name, "feedback",
                               ring=self._bs_ring(),
                               endpoint=self._bs_cell(ep)[0],
                               failed="reject", code=code, final=True)
        else:
            failed = cntl.failed() and code != berr.ERPCTIMEDOUT
            self._lb.feedback(ep, cntl.latency_us(), cntl.failed())
            self._breakers.on_call(ep, failed)
            if _bs.enabled():
                _bs.ring_event(self._stats_name, "feedback",
                               ring=self._bs_ring(),
                               endpoint=self._bs_cell(ep)[0],
                               failed=cntl.failed(), code=code,
                               latency_us=cntl.latency_us(), final=True)
        # every selection must be matched by exactly one feedback or
        # abandon: attempts that never produced an observation (a backup
        # request that lost the race) return their inflight slot, or an
        # inflight-tracking LB would depress that server's weight
        # forever. Multiset difference: tried selections minus delivered
        # feedbacks (attempt failures + the final one above).
        fed = fed_snapshot
        fed.append(ep)
        for s in tried:
            if s in fed:
                fed.remove(s)
            else:
                self._lb.abandon(s)
                if _bs.enabled():
                    _bs.ring_event(self._stats_name, "abandon",
                                   endpoint=_bs.ep_key(s),
                                   why="backup/retry lost the race")

    def close(self):
        self._ns.stop()
        self._health.stop()
        with self._sockets_lock:
            sockets, self._sockets = dict(self._sockets), {}
        for s in sockets.values():
            if not s.failed:
                s.set_failed(ConnectionError("channel closed"))
