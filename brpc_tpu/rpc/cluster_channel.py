"""ClusterChannel: Channel over a naming service + load balancer
(Channel::Init(ns_url, lb_name) + details/load_balancer_with_naming.*).

Per (re)issue: excluded = circuit-breaker-isolated + already-tried (retry
goes elsewhere); the LB picks; completion feeds latency back to the LB and
the breaker. Failed endpoints enter the health checker, which probes them
with backoff and revives them (details/health_check.cpp:59-146).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from brpc_tpu.butil.endpoint import EndPoint
from brpc_tpu.fiber import TaskControl
from brpc_tpu.rpc import errno_codes as berr
from brpc_tpu.rpc.channel import Channel, ChannelOptions
from brpc_tpu.rpc.circuit_breaker import ClusterBreakers
from brpc_tpu.rpc.controller import Controller
from brpc_tpu.rpc.health_check import HealthChecker
from brpc_tpu.rpc.load_balancer import LoadBalancer, new_load_balancer
from brpc_tpu.rpc.naming import NamingServiceThread
from brpc_tpu.transport.socket import Socket, create_client_socket


class ClusterChannel(Channel):
    def __init__(self, naming_url: str, load_balancer: str | LoadBalancer = "rr",
                 options: Optional[ChannelOptions] = None,
                 control: Optional[TaskControl] = None):
        super().__init__(address=None, options=options, control=control)
        self._lb = (load_balancer if isinstance(load_balancer, LoadBalancer)
                    else new_load_balancer(load_balancer))
        self._breakers = ClusterBreakers()
        self._sockets: Dict[EndPoint, Socket] = {}
        self._sockets_lock = threading.Lock()
        self._servers: list = []
        self._health = HealthChecker(
            control=self._control,
            app_check=self.options.app_health_check)
        self._ns = NamingServiceThread(naming_url, control=self._control)
        self._ns.watch(self._on_servers)
        self._ns.wait_first_update(5.0)

    # ------------------------------------------------------------- naming
    def _on_servers(self, servers):
        ns_filter = getattr(self.options, "ns_filter", None)
        if ns_filter is not None:
            # naming_service_filter.h Accept(): rejected servers never
            # reach the LB (filtered at list-reset, not at pick time)
            servers = [ep for ep in servers if ns_filter(ep)]
        self._servers = servers
        self._lb.reset_servers(servers)
        self._health.retain(servers)

    def servers(self):
        return list(self._servers)

    # ----------------------------------------------------------- selection
    def _pick_socket(self, cntl: Controller) -> Socket:
        exclude = set(cntl.tried_servers)
        exclude |= self._breakers.isolated_set(self._servers)
        exclude |= self._health.dead_set()
        key = getattr(cntl, "request_key", None)
        ep = self._lb.select_server(exclude or None, request_key=key)
        if ep is None:
            # every server excluded: last resort, try anyone the LB knows
            ep = self._lb.select_server(None, request_key=key)
        if ep is None:
            raise ConnectionError("no server available")
        # a backup attempt can lose the race with the primary response:
        # once the completion sweep has run (it records, under the same
        # lock, how many tried entries it accounted for), nobody will
        # ever return THIS selection's inflight slot — return it here
        # and abort the attempt instead of leaking it (which would
        # starve la-weighted servers)
        with cntl._lb_lock:
            if cntl._lb_swept_n is not None:
                self._lb.abandon(ep)
                raise ConnectionError("call already completed "
                                      "(late backup/retry attempt dropped)")
            cntl.tried_servers.append(ep)
        if self._on_call_complete not in cntl._complete_hooks:
            cntl._add_complete_hook(self._on_call_complete)
        return self._socket_for(ep)

    def _socket_for(self, ep: EndPoint) -> Socket:
        from brpc_tpu.rpc.channel import connect_dedup

        def _make():
            s = create_client_socket(ep, on_input=self._messenger.on_new_messages,
                                     control=self._control)
            from brpc_tpu.rpc.channel import client_fast_drain_hook
            s.fast_drain = client_fast_drain_hook(self.options)
            s.on_failed(lambda sock, ep=ep: self._on_socket_failed(ep))
            return s

        def _write(s):
            self._sockets[ep] = s

        return connect_dedup(self._sockets_lock,
                             lambda: self._sockets.get(ep), _write, _make)

    def _on_socket_failed(self, ep: EndPoint):
        self._health.mark_dead(ep)

    # ------------------------------------------------------------ feedback
    def _on_attempt_failed(self, cntl: Controller, code: int, text: str,
                           failed_ep=None):
        """Intermediate retry attempts: the failed server must hear about
        it (else it never isolates while retries keep saving the call).
        Attribution prefers the endpoint the failure path captured — a
        concurrent backup selection can make tried_servers[-1] a
        different (healthy) server."""
        with cntl._lb_lock:
            tried = cntl.tried_servers
            if failed_ep is not None and failed_ep in tried:
                ep = failed_ep
            elif tried:
                ep = tried[-1]
            else:
                return
            cntl._lb_fed.append(ep)
        self._lb.feedback(ep, cntl.latency_us(), True)
        self._breakers.on_call(ep, failed=True)

    def _on_call_complete(self, cntl: Controller):
        # the marker and the tried snapshot are taken under the same
        # lock _pick_socket appends under: a late backup attempt either
        # lands before this (and is swept here) or sees the marker and
        # returns its own slot — no in-between
        with cntl._lb_lock:
            cntl._lb_swept_n = len(cntl.tried_servers)
            tried = list(cntl.tried_servers)
            fed_snapshot = list(cntl._lb_fed)
        if not tried:
            return
        # attribute the final observation to the server whose RESPONSE
        # completed the call (with a backup in flight, the last-selected
        # server is often the losing one); timeouts/failures have no
        # responder and fall back to the last attempt
        ep = cntl.responded_server
        if ep is None or ep not in tried:
            ep = tried[-1]
        if cntl.error_code == berr.ECANCELED:
            # cancellation is client-local: no server failed, and the
            # truncated latency is meaningless — abandon every selection
            # (returns inflight slots without polluting stats) instead
            # of feeding the LB/breaker a bogus observation
            for s in tried:
                if s in fed_snapshot:
                    fed_snapshot.remove(s)
                else:
                    self._lb.abandon(s)
            return
        failed = cntl.failed() and cntl.error_code != berr.ERPCTIMEDOUT
        self._lb.feedback(ep, cntl.latency_us(), cntl.failed())
        self._breakers.on_call(ep, failed)
        # every selection must be matched by exactly one feedback or
        # abandon: attempts that never produced an observation (a backup
        # request that lost the race) return their inflight slot, or an
        # inflight-tracking LB would depress that server's weight
        # forever. Multiset difference: tried selections minus delivered
        # feedbacks (attempt failures + the final one above).
        fed = fed_snapshot
        fed.append(ep)
        for s in tried:
            if s in fed:
                fed.remove(s)
            else:
                self._lb.abandon(s)

    def close(self):
        self._ns.stop()
        self._health.stop()
        with self._sockets_lock:
            sockets, self._sockets = dict(self._sockets), {}
        for s in sockets.values():
            if not s.failed:
                s.set_failed(ConnectionError("channel closed"))
