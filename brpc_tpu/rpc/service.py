"""Service & method registration.

The reference registers protobuf Services whose methods arrive via
CallMethod (server.h AddService). We register named methods with optional
protobuf request/response classes; handlers are sync or async callables
``handler(cntl, request) -> response`` where response may be bytes, an
IOBuf, or a protobuf message. Device arrays ride on the controller
(cntl.request_device_arrays / cntl.response_device_arrays).
"""

from __future__ import annotations

import inspect
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional


@dataclass
class Method:
    name: str
    handler: Callable
    request_class: Optional[type] = None
    response_class: Optional[type] = None
    # precomputed at registration: per-request inspect.iscoroutinefunction
    # is measurable on the dispatch hot path
    is_coroutine: bool = False
    full_name: str = ""   # "Service.Method", set by Server.add_service
    # native fast-serve kind. "echo" declares reflection semantics
    # (response payload = request payload, attachment reflected), which
    # lets the server serve small frames for this method entirely in C
    # (fastcore serve_scan — request parse, dispatch and response pack
    # never cross the interpreter, like the reference's compiled
    # handlers inside in-place processing). The Python handler remains
    # the implementation for big frames and slow-featured requests, and
    # MUST have the same semantics.
    native_kind: Optional[str] = None


class Service:
    def __init__(self, name: str):
        self.name = name
        self.methods: Dict[str, Method] = {}

    def register_method(self, name: str, handler: Callable,
                        request_class: Optional[type] = None,
                        response_class: Optional[type] = None,
                        native: Optional[str] = None) -> None:
        if native is not None and native != "echo":
            raise ValueError(f"unknown native method kind {native!r}")
        self.methods[name] = Method(
            name, handler, request_class, response_class,
            is_coroutine=inspect.iscoroutinefunction(handler),
            native_kind=native)

    def method(self, name: Optional[str] = None, request_class=None,
               response_class=None, native: Optional[str] = None):
        """Decorator: ``@svc.method()`` over ``def Echo(cntl, req): ...``

        ``native="echo"`` additionally declares the method as a
        reflection echo the server may serve natively (see Method)."""
        def deco(fn):
            self.register_method(name or fn.__name__, fn, request_class,
                                 response_class, native=native)
            return fn
        return deco


def service_from_object(obj: Any, name: Optional[str] = None) -> Service:
    """Build a Service from an object's public methods (duck-typed
    convenience for hand-written service classes)."""
    svc = Service(name or type(obj).__name__)
    for attr in dir(obj):
        if attr.startswith("_"):
            continue
        fn = getattr(obj, attr)
        if callable(fn):
            svc.register_method(attr, fn)
    return svc
