"""Load balancers (brpc/load_balancer.h:35; impls in brpc/policy/).

Server lists live in a DoublyBufferedData snapshot so selection is
lock-free, exactly as the reference keeps them. ``select_server`` takes an
exclusion set (failed/tried servers for retries) and returns an EndPoint;
``feedback`` reports call latency/errors for adaptive balancers.
"""

from __future__ import annotations

import bisect
import hashlib
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from brpc_tpu.butil.doubly_buffered import DoublyBufferedData
from brpc_tpu.butil.endpoint import EndPoint
from brpc_tpu.butil.fast_rand import fast_rand_less_than


def _ep_weight(s: EndPoint) -> int:
    """Endpoint extra 'w' as an int weight >= 1; tolerant of float
    strings from naming sources and of malformed/inf values (a bad
    weight must never take down a naming-reset path)."""
    try:
        w = float(s.extra("w", "1") or "1")
    except (TypeError, ValueError):
        return 1
    if w != w or w in (float("inf"), float("-inf")):
        return 1
    # capped: wrr expands to [server] * weight, so an absurd value must
    # degrade to a bounded list, not an OOM
    return min(10000, max(1, int(w)))


class LoadBalancer:
    def reset_servers(self, servers: Sequence[EndPoint]) -> None:
        raise NotImplementedError

    def select_server(self, exclude: Optional[set] = None,
                      request_key: Optional[bytes] = None) -> Optional[EndPoint]:
        raise NotImplementedError

    def feedback(self, server: EndPoint, latency_us: float, failed: bool) -> None:
        pass

    def abandon(self, server: EndPoint) -> None:
        """A selected attempt finished without a latency observation
        (backup request lost the race, stale retry): inflight-tracking
        balancers must return the slot without polluting their stats."""
        pass

    def revive(self, server: EndPoint) -> None:
        """The health checker revived this endpoint: balancers holding
        adaptive per-server state may reset it to a probe-friendly
        value (a node that died with a penalty-saturated estimate
        would otherwise return at ~zero weight and never earn the
        feedback that proves it healthy again)."""
        pass

    def feedback_reject(self, server: EndPoint) -> None:
        """The server SHED this attempt (ELIMIT / queue-delay shed /
        write overcrowding) — failure-without-latency: the slot
        returns and the reject is counted, but the microsecond reject
        round-trip must not enter the latency estimate (a shedding
        node would look FAST) and the overload must not be penalized
        like breakage (the error-penalty EWMA kick that isolates
        actually-broken nodes). Default: indistinguishable from
        abandon for balancers with no reject-aware state."""
        self.abandon(server)

    def decision_info(self, server: EndPoint) -> Optional[dict]:
        """Optional per-server decision factors for the LB trace ring
        (/lb_trace): balancers that weigh servers (la) report WHY this
        one won — weight, latency estimate, inflight. None = the
        balancer has nothing beyond its name (rr/random/hash)."""
        return None


class _SnapshotLB(LoadBalancer):
    def __init__(self):
        self._servers: DoublyBufferedData = DoublyBufferedData(tuple())

    def reset_servers(self, servers):
        snapshot = tuple(servers)
        self._servers.modify(lambda _: snapshot)
        self._on_reset(snapshot)

    def _on_reset(self, snapshot):
        pass

    def _alive(self, exclude):
        servers = self._servers.read()
        if not exclude:
            return servers
        return tuple(s for s in servers if s not in exclude)


class RoundRobinLB(_SnapshotLB):
    name = "rr"

    def __init__(self):
        super().__init__()
        self._idx = 0
        self._lock = threading.Lock()

    def select_server(self, exclude=None, request_key=None):
        servers = self._alive(exclude)
        if not servers:
            return None
        with self._lock:
            self._idx = (self._idx + 1) % len(servers)
            return servers[self._idx]


class RandomLB(_SnapshotLB):
    name = "random"

    def select_server(self, exclude=None, request_key=None):
        servers = self._alive(exclude)
        if not servers:
            return None
        return servers[fast_rand_less_than(len(servers))]


class WeightedRoundRobinLB(_SnapshotLB):
    """wrr — weight from endpoint extra 'w' (default 1)."""

    name = "wrr"

    def __init__(self):
        super().__init__()
        self._expanded: Tuple[EndPoint, ...] = ()
        self._idx = 0
        self._lock = threading.Lock()

    def _on_reset(self, snapshot):
        out: List[EndPoint] = []
        for s in snapshot:
            out.extend([s] * _ep_weight(s))
        self._expanded = tuple(out)

    def select_server(self, exclude=None, request_key=None):
        servers = self._expanded
        if exclude:
            servers = tuple(s for s in servers if s not in exclude)
        if not servers:
            return None
        with self._lock:
            self._idx = (self._idx + 1) % len(servers)
            return servers[self._idx]


class WeightedRandomLB(_SnapshotLB):
    """wr — weight-proportional random pick
    (policy/weighted_randomized_load_balancer.cpp); weight from
    endpoint extra 'w' (default 1), matching wrr's convention."""

    name = "wr"

    def __init__(self):
        super().__init__()
        # (server, weight) pairs published as ONE tuple so a reset can
        # never mispair weights with a concurrently-read server list
        self._weighted: Tuple[Tuple[EndPoint, int], ...] = ()

    def _on_reset(self, snapshot):
        self._weighted = tuple((s, _ep_weight(s)) for s in snapshot)

    def select_server(self, exclude=None, request_key=None):
        pool = [(s, w) for s, w in self._weighted
                if not exclude or s not in exclude]
        if not pool:
            return None
        total = sum(w for _, w in pool)
        pick = fast_rand_less_than(total)
        for s, w in pool:
            pick -= w
            if pick < 0:
                return s
        return pool[-1][0]


class ConsistentHashLB(_SnapshotLB):
    """c_murmurhash-style ketama ring (policy/hasher.cpp) — 100 virtual
    nodes per server; request_key picks the ring position."""

    name = "c_hash"
    VIRTUAL_NODES = 100

    def __init__(self):
        super().__init__()
        self._ring: List[Tuple[int, EndPoint]] = []

    @staticmethod
    def _hash(data: bytes) -> int:
        return int.from_bytes(hashlib.md5(data).digest()[:8], "big")

    def _on_reset(self, snapshot):
        ring = []
        for s in snapshot:
            for v in range(self.VIRTUAL_NODES):
                ring.append((self._hash(f"{s}#{v}".encode()), s))
        ring.sort(key=lambda t: t[0])
        self._ring = ring

    def select_server(self, exclude=None, request_key=None):
        ring = self._ring
        if not ring:
            return None
        h = self._hash(request_key or b"")
        idx = bisect.bisect_left(ring, (h, ))
        n = len(ring)
        for i in range(n):
            _, s = ring[(idx + i) % n]
            if not exclude or s not in exclude:
                return s
        return None


class MurmurHashLB(ConsistentHashLB):
    """c_murmurhash — the same ketama ring keyed by murmur3
    (policy/hasher.cpp MurmurHash32), native-accelerated via
    brpc_tpu.native."""

    name = "c_murmurhash"

    @staticmethod
    def _hash(data: bytes) -> int:
        from brpc_tpu.butil.hash import murmur3_32of128
        return murmur3_32of128(data)


class _Fenwick:
    """Partial-sum tree over float weights: O(log n) point update +
    prefix-sum descent (the divide tree of
    policy/locality_aware_load_balancer.cpp, where selection walks
    left/right by accumulated weight)."""

    def __init__(self, n: int):
        self.n = n
        self._t = [0.0] * (n + 1)
        self._w = [0.0] * n          # raw weights for point reads

    def set(self, i: int, w: float) -> None:
        delta = w - self._w[i]
        self._w[i] = w
        i += 1
        while i <= self.n:
            self._t[i] += delta
            i += i & (-i)

    def get(self, i: int) -> float:
        return self._w[i]

    @property
    def total(self) -> float:
        s = 0.0
        i = self.n
        while i > 0:
            s += self._t[i]
            i -= i & (-i)
        return s

    def find(self, target: float) -> int:
        """Index whose weight range contains `target`
        (0 <= target < total), by binary tree descent."""
        idx = 0
        bit = 1
        while bit * 2 <= self.n:
            bit *= 2
        while bit:
            nxt = idx + bit
            if nxt <= self.n and self._t[nxt] <= target:
                target -= self._t[nxt]
                idx = nxt
            bit //= 2
        return min(idx, self.n - 1)


class LocalityAwareLB(_SnapshotLB):
    """la — locality-aware weighted pick
    (policy/locality_aware_load_balancer.cpp): weight ~
    1 / (EMA latency x (inflight + 1)), held in a partial-sum tree for
    O(log n) selection. Selecting a server counts an in-flight request
    against it immediately — a server accumulating un-answered requests
    loses weight before its latency EMA even moves — and feedback()
    returns the slot and folds the observed latency in (errors count as
    a sharp latency penalty). New servers start at the cluster's best
    observed latency so they get probed quickly."""

    name = "la"
    ALPHA = 0.2
    DEFAULT_LAT_US = 1000.0
    ERROR_PENALTY_US = 1e6
    # penalty ceiling: cur*10 per failure compounds, and a node that
    # dies under sustained traffic would ride the exponential to
    # float-inf — weight exactly 0.0, which makes the whole cluster
    # unselectable in the all-excluded fallback during a full outage
    # and leaves the node at zero weight forever after revival
    MAX_PENALTY_US = 6e7

    def __init__(self):
        super().__init__()
        self._lock = threading.Lock()
        self._lat: Dict[EndPoint, float] = {}
        self._inflight: Dict[EndPoint, int] = {}
        self._rejects: Dict[EndPoint, int] = {}   # overload sheds seen
        self._tree: Optional[_Fenwick] = None
        self._order: list = []          # index -> server
        self._index: Dict[EndPoint, int] = {}

    # ----------------------------------------------------------- weights
    def _weight(self, s) -> float:
        lat = max(self._lat.get(s, self.DEFAULT_LAT_US), 1.0)
        return 1e9 / (lat * (self._inflight.get(s, 0) + 1))

    def _on_reset(self, snapshot):
        with self._lock:
            keep = set(snapshot)
            self._lat = {s: v for s, v in self._lat.items() if s in keep}
            self._inflight = {s: v for s, v in self._inflight.items()
                              if s in keep}
            self._rejects = {s: v for s, v in self._rejects.items()
                             if s in keep}
            self._order = list(snapshot)
            self._index = {s: i for i, s in enumerate(self._order)}
            self._tree = _Fenwick(len(self._order)) if self._order else None
            best = min(self._lat.values(), default=self.DEFAULT_LAT_US)
            for i, s in enumerate(self._order):
                self._lat.setdefault(s, best)   # optimistic probe weight
                self._tree.set(i, self._weight(s))

    # ---------------------------------------------------------- protocol
    def decision_info(self, server):
        with self._lock:
            lat = self._lat.get(server)
            if lat is None:
                return None
            info = {"weight": round(self._weight(server), 3),
                    "lat_ewma_us": round(lat, 1),
                    "inflight": self._inflight.get(server, 0)}
            nrej = self._rejects.get(server, 0)
            if nrej:
                info["rejects"] = nrej
            return info

    def revive(self, server):
        """Back from the dead: restart the latency estimate at the
        cluster's best observed latency (the same optimistic probe
        weight new servers get in _on_reset) — the penalty-saturated
        EWMA the node died with would otherwise keep its weight near
        zero, starving it of the very feedback that could clear it."""
        with self._lock:
            if server not in self._index:
                return
            best = min((v for s, v in self._lat.items() if s != server),
                       default=self.DEFAULT_LAT_US)
            self._lat[server] = min(best, self.MAX_PENALTY_US)
            if self._tree is not None:
                self._tree.set(self._index[server],
                               self._weight(server))

    def feedback_reject(self, server):
        """Overload shed: return the in-flight slot and count the
        reject, but leave the latency EWMA alone — the distinction the
        overload-control loop depends on (a shedding node stops being
        selected because its inflight stays high relative to the calls
        it actually answers, not because it looks broken)."""
        with self._lock:
            inf = self._inflight.get(server, 0)
            if inf > 0:
                self._inflight[server] = inf - 1
            self._rejects[server] = self._rejects.get(server, 0) + 1
            i = self._index.get(server)
            if i is not None and self._tree is not None:
                self._tree.set(i, self._weight(server))

    def abandon(self, server):
        with self._lock:
            inf = self._inflight.get(server, 0)
            if inf > 0:
                self._inflight[server] = inf - 1
            i = self._index.get(server)
            if i is not None and self._tree is not None:
                self._tree.set(i, self._weight(server))

    def feedback(self, server, latency_us, failed):
        with self._lock:
            inf = self._inflight.get(server, 0)
            if inf > 0:
                self._inflight[server] = inf - 1
            cur = self._lat.get(server, self.DEFAULT_LAT_US)
            sample = (latency_us if not failed
                      else min(self.MAX_PENALTY_US,
                               max(cur * 10, self.ERROR_PENALTY_US)))
            self._lat[server] = (1 - self.ALPHA) * cur + self.ALPHA * sample
            i = self._index.get(server)
            if i is not None and self._tree is not None:
                self._tree.set(i, self._weight(server))

    def select_server(self, exclude=None, request_key=None):
        with self._lock:
            tree = self._tree
            if tree is None or not self._order:
                return None
            masked: list = []
            try:
                if exclude:
                    # temporarily zero excluded weights; restored below
                    for s in exclude:
                        i = self._index.get(s)
                        if i is not None and tree.get(i) > 0:
                            masked.append((i, tree.get(i)))
                            tree.set(i, 0.0)
                total = tree.total
                if total <= 0:
                    return None
                r = (fast_rand_less_than(1 << 30) / float(1 << 30)) * total
                chosen = self._order[tree.find(r)]
            finally:
                for i, w in masked:
                    tree.set(i, w)
            if exclude and chosen in exclude:
                return None
            # count the in-flight request now: un-answered requests push
            # weight down before latency feedback even arrives
            self._inflight[chosen] = self._inflight.get(chosen, 0) + 1
            tree.set(self._index[chosen], self._weight(chosen))
            return chosen


_factories = {
    "rr": RoundRobinLB,
    "random": RandomLB,
    "wrr": WeightedRoundRobinLB,
    "c_hash": ConsistentHashLB,
    "c_murmurhash": MurmurHashLB,
    "wr": WeightedRandomLB,
    "la": LocalityAwareLB,
}


def new_load_balancer(name: str) -> LoadBalancer:
    cls = _factories.get(name)
    if cls is None:
        raise ValueError(f"unknown load balancer {name!r}")
    return cls()


def register_load_balancer(name: str, factory) -> None:
    _factories[name] = factory
