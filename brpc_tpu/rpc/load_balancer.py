"""Load balancers (brpc/load_balancer.h:35; impls in brpc/policy/).

Server lists live in a DoublyBufferedData snapshot so selection is
lock-free, exactly as the reference keeps them. ``select_server`` takes an
exclusion set (failed/tried servers for retries) and returns an EndPoint;
``feedback`` reports call latency/errors for adaptive balancers.
"""

from __future__ import annotations

import bisect
import hashlib
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from brpc_tpu.butil.doubly_buffered import DoublyBufferedData
from brpc_tpu.butil.endpoint import EndPoint
from brpc_tpu.butil.fast_rand import fast_rand_less_than


class LoadBalancer:
    def reset_servers(self, servers: Sequence[EndPoint]) -> None:
        raise NotImplementedError

    def select_server(self, exclude: Optional[set] = None,
                      request_key: Optional[bytes] = None) -> Optional[EndPoint]:
        raise NotImplementedError

    def feedback(self, server: EndPoint, latency_us: float, failed: bool) -> None:
        pass


class _SnapshotLB(LoadBalancer):
    def __init__(self):
        self._servers: DoublyBufferedData = DoublyBufferedData(tuple())

    def reset_servers(self, servers):
        snapshot = tuple(servers)
        self._servers.modify(lambda _: snapshot)
        self._on_reset(snapshot)

    def _on_reset(self, snapshot):
        pass

    def _alive(self, exclude):
        servers = self._servers.read()
        if not exclude:
            return servers
        return tuple(s for s in servers if s not in exclude)


class RoundRobinLB(_SnapshotLB):
    name = "rr"

    def __init__(self):
        super().__init__()
        self._idx = 0
        self._lock = threading.Lock()

    def select_server(self, exclude=None, request_key=None):
        servers = self._alive(exclude)
        if not servers:
            return None
        with self._lock:
            self._idx = (self._idx + 1) % len(servers)
            return servers[self._idx]


class RandomLB(_SnapshotLB):
    name = "random"

    def select_server(self, exclude=None, request_key=None):
        servers = self._alive(exclude)
        if not servers:
            return None
        return servers[fast_rand_less_than(len(servers))]


class WeightedRoundRobinLB(_SnapshotLB):
    """wrr — weight from endpoint extra 'w' (default 1)."""

    name = "wrr"

    def __init__(self):
        super().__init__()
        self._expanded: Tuple[EndPoint, ...] = ()
        self._idx = 0
        self._lock = threading.Lock()

    def _on_reset(self, snapshot):
        out: List[EndPoint] = []
        for s in snapshot:
            w = int(s.extra("w", "1") or "1")
            out.extend([s] * max(1, w))
        self._expanded = tuple(out)

    def select_server(self, exclude=None, request_key=None):
        servers = self._expanded
        if exclude:
            servers = tuple(s for s in servers if s not in exclude)
        if not servers:
            return None
        with self._lock:
            self._idx = (self._idx + 1) % len(servers)
            return servers[self._idx]


class ConsistentHashLB(_SnapshotLB):
    """c_murmurhash-style ketama ring (policy/hasher.cpp) — 100 virtual
    nodes per server; request_key picks the ring position."""

    name = "c_hash"
    VIRTUAL_NODES = 100

    def __init__(self):
        super().__init__()
        self._ring: List[Tuple[int, EndPoint]] = []

    @staticmethod
    def _hash(data: bytes) -> int:
        return int.from_bytes(hashlib.md5(data).digest()[:8], "big")

    def _on_reset(self, snapshot):
        ring = []
        for s in snapshot:
            for v in range(self.VIRTUAL_NODES):
                ring.append((self._hash(f"{s}#{v}".encode()), s))
        ring.sort(key=lambda t: t[0])
        self._ring = ring

    def select_server(self, exclude=None, request_key=None):
        ring = self._ring
        if not ring:
            return None
        h = self._hash(request_key or b"")
        idx = bisect.bisect_left(ring, (h, ))
        n = len(ring)
        for i in range(n):
            _, s = ring[(idx + i) % n]
            if not exclude or s not in exclude:
                return s
        return None


class MurmurHashLB(ConsistentHashLB):
    """c_murmurhash — the same ketama ring keyed by murmur3
    (policy/hasher.cpp MurmurHash32), native-accelerated via
    brpc_tpu.native."""

    name = "c_murmurhash"

    @staticmethod
    def _hash(data: bytes) -> int:
        from brpc_tpu.butil.hash import murmur3_32of128
        return murmur3_32of128(data)


class LocalityAwareLB(_SnapshotLB):
    """la — latency-weighted pick (policy/locality_aware_load_balancer.cpp
    simplified): weight ~ 1/EMA(latency); errors decay weight sharply."""

    name = "la"
    ALPHA = 0.2

    def __init__(self):
        super().__init__()
        self._lat: Dict[EndPoint, float] = {}
        self._lock = threading.Lock()

    def feedback(self, server, latency_us, failed):
        with self._lock:
            cur = self._lat.get(server, 1000.0)
            sample = latency_us if not failed else max(cur * 10, 1e6)
            self._lat[server] = (1 - self.ALPHA) * cur + self.ALPHA * sample

    def select_server(self, exclude=None, request_key=None):
        servers = self._alive(exclude)
        if not servers:
            return None
        with self._lock:
            weights = [1.0 / max(self._lat.get(s, 1000.0), 1.0) for s in servers]
        total = sum(weights)
        r = (fast_rand_less_than(1 << 30) / float(1 << 30)) * total
        acc = 0.0
        for s, w in zip(servers, weights):
            acc += w
            if r <= acc:
                return s
        return servers[-1]


_factories = {
    "rr": RoundRobinLB,
    "random": RandomLB,
    "wrr": WeightedRoundRobinLB,
    "c_hash": ConsistentHashLB,
    "c_murmurhash": MurmurHashLB,
    "la": LocalityAwareLB,
}


def new_load_balancer(name: str) -> LoadBalancer:
    cls = _factories.get(name)
    if cls is None:
        raise ValueError(f"unknown load balancer {name!r}")
    return cls()


def register_load_balancer(name: str, factory) -> None:
    _factories[name] = factory
