"""Client-side response processing (ProcessRpcResponse,
policy/baidu_rpc_protocol.cpp:565 -> OnVersionedRPCReturned)."""

from __future__ import annotations

import time

from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.protocol.tpu_std import RpcMessage, unpack_inline_device_arrays
from brpc_tpu.rpc import errno_codes as berr
from brpc_tpu.rpc.controller import address_call, take_call
from brpc_tpu.transport.syscall_stats import (note_rpc_messages as
                                              _note_rpc_messages)


class PayloadBytes(bytes):
    """bytes carrying the read surface response consumers use
    (``to_bytes``/``size``) — the fast response path hands payloads over
    without IOBuf/Block machinery; every documented read works
    identically (it IS bytes)."""

    __slots__ = ()

    def to_bytes(self) -> bytes:
        return self

    @property
    def size(self) -> int:
        return len(self)


def make_client_fast_drain():
    """Build the client-side chunk fast lane (Socket.fast_drain for
    chunk-handoff transports like mem://): pull the writer's exact bytes
    objects, scan_frames them in one C pass, and complete the response
    records through process_response_fast — no portal wrap/view/pop, no
    turbo-lane indirection. Anything that isn't a clean run of fast
    responses re-injects into the portal for the classic machinery.
    Returns None when the extension is unavailable."""
    from brpc_tpu.native import fastcore as _fc_loader
    fc = _fc_loader.get()
    scan = getattr(fc, "scan_frames", None) if fc is not None else None
    from brpc_tpu.protocol.tpu_std import (MAGIC, SMALL_FRAME_MAX,
                                           STREAM_SCAN_MAX)
    if scan is not None:
        try:
            scan(b"", MAGIC, 0, 0, 0, 1)   # materialize support probe
        except TypeError:
            scan = None                    # prebuilt-stale extension
    if scan is None:
        return None
    from brpc_tpu.rpc.stream import process_stream_frame_fast
    from brpc_tpu.transport.socket import pull_chunks as _pull_chunks

    def fast_drain(sock) -> bool:
        if sock.input_portal or sock.input_need:
            return False
        data, handled = _pull_chunks(sock)   # self-disables on fd conns
        if data is None:
            return handled
        consumed, frames = scan(data, MAGIC, SMALL_FRAME_MAX, 128,
                                STREAM_SCAN_MAX, 1)
        if any(f[0] == 0 for f in frames):
            # a request-shaped frame on a client socket: hand the WHOLE
            # run to the classic machinery in parse order (the records
            # don't carry frame starts, so a partial dispatch could not
            # find its cut point)
            sock.input_portal.append_user_data(data)
            return False
        if frames:
            # these completions bypass record_dispatch_batch: stamp the
            # syscalls_per_rpc denominator here (transport/syscall_stats)
            _note_rpc_messages(len(frames))
        for f in frames:
            if f[0] == 2:
                # live stream frame: dispatched in parse order, like
                # the turbo lane
                _, sid, seq, credits, sclose, pay, att = f
                process_stream_frame_fast(sid, seq, credits, sclose,
                                          pay, att)
                continue
            _, cid, ec, et, pay, att = f
            process_response_fast(cid, ec, et, pay, att, sock)
        if consumed == len(data):
            if frames:
                sock.__dict__["_fdrain_defer_streak"] = 0
            return True
        # tail the scanner stopped at (partial frame / slow meta): the
        # classic path judges it from the stop offset — a connection
        # whose responses are ALWAYS slow-shaped stops paying the lane
        if not frames:
            streak = sock.__dict__.get("_fdrain_defer_streak", 0) + 1
            if streak >= 16:
                sock.fast_drain = None
            else:
                sock._fdrain_defer_streak = streak
        sock.input_portal.append_user_data(data[consumed:])
        return False

    return fast_drain


def process_response_fast(cid: int, err_code: int, err_text, payload: bytes,
                          att: bytes, socket) -> None:
    """Complete a call from scan_frames response fields — no RpcMeta
    object, no portal cuts. The scanner guarantees no compression, no
    stream settings, no device payloads; the error path (retry/policy
    interplay) reuses the classic flow via a synthesized message."""
    cntl = address_call(cid)
    if cntl is None:
        return  # stale: the call already completed (timeout/backup winner)
    ch = cntl._owner_channel
    if ch is not None and ch._adm_cache:
        # a response that rode the FAST lane cannot carry an admission
        # threshold (the C scanner defers unknown response-meta fields
        # to the classic parser) — its absence here is therefore
        # definitive: the backend relaxed, clear the cached entry
        ch._track_admission_threshold(socket.remote_endpoint,
                                      cntl._service_name, 0)
    if err_code:
        from brpc_tpu.protocol.proto import tpu_rpc_meta_pb2 as pb
        meta = pb.RpcMeta()
        meta.correlation_id = cid
        meta.response.error_code = err_code
        meta.response.error_text = err_text or ""
        process_response(None, RpcMessage(meta, IOBuf(), IOBuf()), socket)
        return
    with cntl._arb_lock:
        if take_call(cid) is not cntl:
            return  # raced with timeout/backup completion
    cntl.responded_server = socket.remote_endpoint
    # wire size of the winning response, for the backend stat cell's
    # bytes_in (the completion sweep attributes it to the responder)
    cntl.__dict__["_bs_resp_bytes"] = len(payload) + len(att)
    span = cntl.__dict__.get("_client_span")
    if span is not None:
        span.first_byte_us = time.monotonic_ns() // 1000
    try:
        cntl.response_payload = PayloadBytes(payload)
        if cntl.response_msg is not None:
            cntl.response_msg.ParseFromString(payload)
        if att:
            ab = IOBuf()
            ab.append(att)
            cntl.__dict__["response_attachment"] = ab
    except Exception as e:
        cntl.set_failed(berr.ERESPONSE, f"bad response: {e}")
    if span is not None:
        span.parse_done_us = time.monotonic_ns() // 1000
        span.response_size = len(payload)
    cntl._complete()


def process_response(proto, msg: RpcMessage, socket) -> None:
    cid = msg.meta.correlation_id
    # take FIRST: exactly one response/timer wins the call; stale or
    # concurrent losers never touch the controller (the versioned-id
    # arbitration of OnVersionedRPCReturned, controller.cpp:575)
    cntl = address_call(cid)
    if cntl is None:
        return  # stale: the call already completed (timeout/backup winner)
    has_resp = msg.meta.HasField("response")
    ch = cntl._owner_channel
    if ch is not None:
        # DAGOR threshold piggyback: an overloaded backend stamps its
        # admission threshold on every response — cache it so doomed
        # sends fail fast locally (Channel._doomed_by_threshold); a
        # response WITHOUT the stamp means that backend relaxed, so a
        # non-empty cache clears its entry. The calm common case pays
        # one int read (0) + one empty-dict truthiness check.
        thr = msg.meta.response.admission_threshold if has_resp else 0
        if thr or ch._adm_cache:
            ch._track_admission_threshold(socket.remote_endpoint,
                                          cntl._service_name, thr)
    is_error = has_resp and msg.meta.response.error_code != 0
    if is_error:
        code = msg.meta.response.error_code
        text = msg.meta.response.error_text
        channel = getattr(cntl, "_owner_channel", None)
        if channel is not None:
            # policy consult BEFORE the lock: user policy code must not
            # run while the process-wide timer thread can block on
            # cntl._arb_lock in _on_timeout
            allow = channel._policy_allows(cntl, code, text)
            with cntl._arb_lock:
                if take_call(cid) is not cntl:
                    return  # lost to a concurrent winner
                retrying = channel._retry_taken_call(
                    cntl, code, text, socket.remote_endpoint, allow=allow)
            if retrying:
                # re-registered under a fresh correlation id; issue the
                # new attempt outside the lock (connects can block) —
                # through the backoff gate, like every other retry
                channel._launch_retry(cntl, code, text)
                return
            cntl.responded_server = socket.remote_endpoint
            cntl.set_failed(code, text)
            cntl._complete()
            return
    with cntl._arb_lock:
        if take_call(cid) is not cntl:
            return  # raced with timeout/backup completion
    # record the WINNER for LB/breaker attribution: with a backup request
    # in flight, the last-selected server is not necessarily the one
    # whose response completed the call
    cntl.responded_server = socket.remote_endpoint
    # wire size before decompression — the backend cell accounts what
    # the network carried, not what the codec expanded it to
    cntl.__dict__["_bs_resp_bytes"] = msg.payload.size + msg.attachment.size
    span = cntl.__dict__.get("_client_span")
    if span is not None:
        # the frame's cut-time stamp is the closest honest "first
        # response byte" the classic path has (span.h received_us)
        span.first_byte_us = \
            (getattr(msg, "arrival_ns", 0) or time.monotonic_ns()) // 1000
    try:
        _fill_response(cntl, msg, socket)
    except Exception as e:
        # the controller is already out of the pool: it MUST complete here
        # or join() hangs forever (e.g. corrupt compressed payload)
        cntl.set_failed(berr.ERESPONSE, f"bad response: {e}")
    if span is not None:
        span.parse_done_us = time.monotonic_ns() // 1000
        span.response_size = msg.payload.size
    cntl._complete()


def _fill_response(cntl, msg: RpcMessage, socket) -> None:
    if msg.meta.HasField("response") and msg.meta.response.error_code != 0:
        cntl.set_failed(msg.meta.response.error_code,
                        msg.meta.response.error_text)
        # (a piggybacked stream is closed by cntl._complete on failure)
    else:
        if msg.meta.compress_type:
            from brpc_tpu.butil.iobuf import IOBuf
            from brpc_tpu.rpc.compress import decompress
            raw = decompress(msg.payload.to_bytes(), msg.meta.compress_type)
            msg.payload = IOBuf()
            msg.payload.append(raw)
        cntl.response_payload = msg.payload
        if cntl.response_msg is not None:
            try:
                cntl.response_msg.ParseFromString(msg.payload.to_bytes())
            except Exception as e:
                cntl.set_failed(berr.ERESPONSE, f"cannot parse response: {e}")
        stream = getattr(cntl, "stream", None)
        if stream is not None and msg.meta.HasField("stream_settings"):
            stream.peer_id = msg.meta.stream_settings.stream_id
            stream.bind_socket(socket)
            stream._on_established()
        if msg.meta.device_payloads:
            inline = unpack_inline_device_arrays(msg)
            lane_iter = iter(msg.device_arrays)
            arrays = []
            for dp, inl in zip(msg.meta.device_payloads, inline):
                arrays.append(inl if dp.inline_bytes else next(lane_iter, None))
            cntl.response_device_arrays = arrays
            dr = getattr(msg, "device_recv", None)
            span = cntl.__dict__.get("_client_span")
            if dr is not None and span is not None:
                # the response's device-recv leg as a child of the
                # client span (shared helper; the server-side twin
                # lives in server_dispatch._process_request_body)
                from brpc_tpu.rpc.span import submit_device_recv_span
                submit_device_recv_span(span, dr)
        cntl.response_attachment = msg.attachment
