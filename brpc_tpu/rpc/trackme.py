"""trackme: version phone-home (brpc/trackme.{h,cpp} — clients ping a
trackme server at most once per TrackMe interval; the server replies
with severity + message for known-bad versions).

Disabled by default (flag ``trackme_server`` empty — this environment
has zero egress anyway); point it at a brpc_tpu server exposing
``TrackMeService`` to light it up in a pod."""

from __future__ import annotations

import json
import threading
import time
from typing import Optional

from brpc_tpu.butil.flags import define_flag, flag

define_flag("trackme_server", "", "address of the trackme server "
            "(empty = disabled)")
define_flag("trackme_interval_s", 30.0, "min seconds between pings")

_lock = threading.Lock()
_last_ping = 0.0
_last_result: Optional[dict] = None

TRACKME_OK = 0
TRACKME_WARNING = 1
TRACKME_FATAL = 2


def trackme_service():
    """Server half: a Service answering pings with per-version verdicts
    (install with server.add_service(trackme_service()))."""
    from brpc_tpu import __version__
    from brpc_tpu.rpc.service import Service

    svc = Service("TrackMeService")

    @svc.method()
    def Ping(cntl, request):
        try:
            info = json.loads(bytes(request) or b"{}")
        except ValueError:
            info = {}
        severity = TRACKME_OK
        message = ""
        if info.get("version", __version__) != __version__:
            severity = TRACKME_WARNING
            message = (f"peer runs {info.get('version')}, "
                       f"server runs {__version__}")
        return json.dumps({"severity": severity, "message": message}).encode()

    return svc


def maybe_ping(control=None) -> Optional[dict]:
    """Client half: rate-limited ping; returns the server verdict or None
    when disabled/rate-limited/unreachable (failures never disturb the
    caller — trackme.cpp swallows errors the same way)."""
    global _last_ping, _last_result
    server = flag("trackme_server")
    if not server:
        return None
    now = time.monotonic()
    interval = flag("trackme_interval_s")
    with _lock:
        if now - _last_ping < interval:
            return _last_result
        _last_ping = now
    ok = False
    try:
        from brpc_tpu import __version__
        from brpc_tpu.rpc.channel import Channel, ChannelOptions
        ch = Channel(server, ChannelOptions(timeout_ms=500, max_retry=0),
                     control=control)
        cntl = ch.call_sync("TrackMeService", "Ping",
                            json.dumps({"version": __version__}).encode())
        ch.close()
        if cntl.failed():
            return None
        result = json.loads(cntl.response_payload.to_bytes())
        with _lock:
            _last_result = result
        ok = True
        return result
    except Exception:
        return None
    finally:
        if not ok:
            # a transient failure must not burn the whole interval, but
            # also must not hammer a dead server: retry after a short
            # backoff instead
            retry_after = min(5.0, interval)
            with _lock:
                _last_ping = now - max(0.0, interval - retry_after)
