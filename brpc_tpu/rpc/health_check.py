"""Health checking: failed endpoints are probed with exponential backoff
until a connect succeeds, then revived (details/health_check.cpp:146 —
there a failed Socket enters a periodic HealthCheckTask; revival restores
it to the LB).

With ``app_check`` set, revival is additionally gated on a SUCCESSFUL
RPC, not just a bare TCP connect — the reference's app-level health
check (details/health_check.cpp:59-144, the -health_check_path RPC on
the revived socket): a server that accepts connections but can't answer
stays dead."""

from __future__ import annotations

import random
import threading
from typing import Callable, Dict, Optional, Set, Tuple

from brpc_tpu.butil.endpoint import EndPoint
from brpc_tpu.fiber import TaskControl, global_control, sleep
from brpc_tpu.transport.base import get_transport


def rpc_health_check(service: str = "health", method: str = "Check",
                     timeout_ms: float = 1000.0, request: bytes = b"",
                     protocol: str = "tpu_std", auth_token: str = "",
                     auth=None) -> Callable[[EndPoint], bool]:
    """An app_check that issues one RPC at the endpoint and requires it
    to succeed (the HealthCheckChannel RPC of health_check.cpp:59).
    Pass the cluster's protocol/auth settings — an unauthenticated probe
    against an authenticated server would keep it dead forever."""

    def check(ep: EndPoint) -> bool:
        from brpc_tpu.rpc.channel import Channel, ChannelOptions
        ch = Channel(ep, ChannelOptions(
            protocol=protocol, timeout_ms=timeout_ms, max_retry=0,
            auth_token=auth_token, auth=auth,
            share_connections=False,    # probe on its own connection
            name="health_probe"))       # one stat-cell channel for ALL
        #                                 probes — a per-probe auto name
        #                                 would mint a fresh /backends
        #                                 row per revival attempt
        try:
            cntl = ch.call_sync(service, method, request)
            return not cntl.failed()
        except Exception:
            return False
        finally:
            ch.close()

    return check


class HealthChecker:
    BASE_BACKOFF_S = 0.05
    MAX_BACKOFF_S = 5.0
    # probe spread: each sleep is backoff * [1-J, 1+J). Without it a
    # mass-death of N endpoints (switch bounce, server restart) puts
    # every revival probe on the SAME pure backoff*2 schedule — N
    # synchronized connect storms against a server that is trying to
    # come back (the thundering-herd the reference's
    # -health_check_interval jitter exists to break)
    JITTER = 0.5

    def __init__(self, control: Optional[TaskControl] = None,
                 app_check: Optional[Callable[[EndPoint], bool]] = None,
                 rng: Optional[random.Random] = None,
                 on_event: Optional[Callable[[str, EndPoint], None]] = None):
        self._control = control or global_control()
        self._dead: Set[EndPoint] = set()
        self._checking: Set[EndPoint] = set()
        self._lock = threading.Lock()
        self._stopped = False
        self._app_check = app_check
        self._rng = rng or random.Random()   # injectable: seeded tests
        # observer hook ("dead"/"revived", endpoint) — the cluster
        # channel feeds its LB decision ring with these transitions;
        # fired outside the lock and never allowed to raise into the
        # check fiber
        self._on_event = on_event

    def _emit(self, event: str, ep: EndPoint) -> None:
        cb = self._on_event
        if cb is None:
            return
        try:
            cb(event, ep)
        except Exception:
            pass

    def _jittered(self, backoff: float) -> float:
        return backoff * (1.0 + self.JITTER
                          * (2.0 * self._rng.random() - 1.0))

    def dead_set(self) -> Set[EndPoint]:
        with self._lock:
            return set(self._dead)

    def mark_dead(self, ep: EndPoint) -> None:
        with self._lock:
            if self._stopped or ep in self._checking:
                if ep in self._checking:
                    self._dead.add(ep)
                return
            self._dead.add(ep)
            self._checking.add(ep)
        self._emit("dead", ep)
        self._control.spawn(self._check_loop, ep, name=f"health_{ep.host}")

    def retain(self, servers) -> None:
        """Forget endpoints no longer in the naming list."""
        keep = set(servers)
        with self._lock:
            self._dead &= keep

    async def _check_loop(self, ep: EndPoint):
        backoff = self.BASE_BACKOFF_S
        while not self._stopped:
            with self._lock:
                if ep not in self._dead:
                    break  # dropped from naming or already revived
            await sleep(self._jittered(backoff))
            try:
                conn = get_transport(ep.scheme).connect(ep)
                conn.close()
            except Exception:
                backoff = min(backoff * 2, self.MAX_BACKOFF_S)
                continue
            if self._app_check is not None:
                # connect succeeded but revival needs a working RPC
                # (may block: run it right here in this check fiber)
                try:
                    ok = self._app_check(ep)
                except Exception:
                    ok = False
                if not ok:
                    backoff = min(backoff * 2, self.MAX_BACKOFF_S)
                    continue
            with self._lock:
                self._dead.discard(ep)
            self._emit("revived", ep)
            break
        with self._lock:
            self._checking.discard(ep)
            # an endpoint re-marked dead between revival and this exit
            # would be stranded (mark_dead saw us in _checking and
            # spawned nothing): take the checking slot back and respawn
            respawn = not self._stopped and ep in self._dead
            if respawn:
                self._checking.add(ep)
        if respawn:
            self._control.spawn(self._check_loop, ep,
                                name=f"health_{ep.host}")

    def stop(self):
        self._stopped = True
