"""Health checking: failed endpoints are probed with exponential backoff
until a connect succeeds, then revived (details/health_check.cpp:146 —
there a failed Socket enters a periodic HealthCheckTask; revival restores
it to the LB)."""

from __future__ import annotations

import threading
from typing import Dict, Optional, Set

from brpc_tpu.butil.endpoint import EndPoint
from brpc_tpu.fiber import TaskControl, global_control, sleep
from brpc_tpu.transport.base import get_transport


class HealthChecker:
    BASE_BACKOFF_S = 0.05
    MAX_BACKOFF_S = 5.0

    def __init__(self, control: Optional[TaskControl] = None):
        self._control = control or global_control()
        self._dead: Set[EndPoint] = set()
        self._checking: Set[EndPoint] = set()
        self._lock = threading.Lock()
        self._stopped = False

    def dead_set(self) -> Set[EndPoint]:
        with self._lock:
            return set(self._dead)

    def mark_dead(self, ep: EndPoint) -> None:
        with self._lock:
            if self._stopped or ep in self._checking:
                if ep in self._checking:
                    self._dead.add(ep)
                return
            self._dead.add(ep)
            self._checking.add(ep)
        self._control.spawn(self._check_loop, ep, name=f"health_{ep.host}")

    def retain(self, servers) -> None:
        """Forget endpoints no longer in the naming list."""
        keep = set(servers)
        with self._lock:
            self._dead &= keep

    async def _check_loop(self, ep: EndPoint):
        backoff = self.BASE_BACKOFF_S
        while not self._stopped:
            with self._lock:
                if ep not in self._dead:
                    break  # dropped from naming or already revived
            await sleep(backoff)
            try:
                conn = get_transport(ep.scheme).connect(ep)
                conn.close()
            except Exception:
                backoff = min(backoff * 2, self.MAX_BACKOFF_S)
                continue
            with self._lock:
                self._dead.discard(ep)
            break
        with self._lock:
            self._checking.discard(ep)

    def stop(self):
        self._stopped = True
