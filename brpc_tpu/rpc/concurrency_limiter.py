"""Concurrency limiters (brpc/concurrency_limiter.h:29;
policy/auto_concurrency_limiter.cpp).

``constant``: fixed max in-flight. ``auto``: gradient/Vegas-style — track
the best observed latency; if current latency inflates, shrink the limit,
else grow it (the reference's AutoConcurrencyLimiter in miniature).
"""

from __future__ import annotations

import threading
import time
from typing import Optional


class ConcurrencyLimiter:
    def on_requested(self) -> bool:
        """False = reject with ELIMIT."""
        raise NotImplementedError

    def on_responded(self, latency_us: float, failed: bool) -> None:
        raise NotImplementedError

    @property
    def max_concurrency(self) -> int:
        raise NotImplementedError


class ConstantLimiter(ConcurrencyLimiter):
    def __init__(self, limit: int):
        self._limit = limit
        self._inflight = 0
        self._lock = threading.Lock()

    def on_requested(self) -> bool:
        with self._lock:
            if self._inflight >= self._limit:
                return False
            self._inflight += 1
            return True

    def on_responded(self, latency_us, failed):
        with self._lock:
            self._inflight -= 1

    @property
    def max_concurrency(self):
        return self._limit


class AutoLimiter(ConcurrencyLimiter):
    MIN_LIMIT = 4
    MAX_LIMIT = 4096
    SAMPLE_WINDOW = 100
    INFLATE_TOLERANCE = 1.5     # latency may inflate this much before shrink
    GROW = 1.1
    SHRINK = 0.8

    def __init__(self, initial: int = 32):
        self._limit = float(initial)
        self._inflight = 0
        self._lock = threading.Lock()
        self._best_latency = float("inf")
        self._lat_sum = 0.0
        self._lat_n = 0

    def on_requested(self) -> bool:
        with self._lock:
            if self._inflight >= int(self._limit):
                return False
            self._inflight += 1
            return True

    def on_responded(self, latency_us, failed):
        with self._lock:
            self._inflight -= 1
            if failed:
                return
            self._lat_sum += latency_us
            self._lat_n += 1
            if self._lat_n < self.SAMPLE_WINDOW:
                return
            avg = self._lat_sum / self._lat_n
            self._lat_sum = 0.0
            self._lat_n = 0
            self._best_latency = min(self._best_latency, avg)
            if avg > self._best_latency * self.INFLATE_TOLERANCE:
                self._limit = max(self.MIN_LIMIT, self._limit * self.SHRINK)
                # forgive the past: latency regimes change
                self._best_latency = min(avg, self._best_latency * 1.1)
            else:
                self._limit = min(self.MAX_LIMIT, self._limit * self.GROW)

    @property
    def max_concurrency(self):
        return int(self._limit)


def new_limiter(spec) -> Optional[ConcurrencyLimiter]:
    """spec: None | int | 'constant:N' | 'auto' | 'timeout:MS'
    (AdaptiveMaxConcurrency)."""
    if spec is None:
        return None
    if isinstance(spec, int):
        return ConstantLimiter(spec)
    if isinstance(spec, str):
        if spec == "auto":
            return AutoLimiter()
        if spec.startswith("constant:"):
            return ConstantLimiter(int(spec.split(":", 1)[1]))
        if spec.startswith("timeout:"):
            return TimeoutLimiter(float(spec.split(":", 1)[1]))
        if spec.isdigit():
            return ConstantLimiter(int(spec))
    raise ValueError(f"bad concurrency limiter spec {spec!r}")


class TimeoutLimiter(ConcurrencyLimiter):
    """Timeout-aware limiter (policy/timeout_concurrency_limiter.cpp):
    admit a request only while the expected queueing delay —
    in-flight x EMA latency — still fits inside the timeout budget, so
    requests that would certainly time out in the queue are shed at the
    door instead of wasting a slot."""

    MIN_LIMIT = 2
    EMA_ALPHA = 0.2

    def __init__(self, timeout_ms: float):
        self._timeout_us = float(timeout_ms) * 1e3
        self._ema_us = 0.0
        self._inflight = 0
        self._lock = threading.Lock()

    def on_requested(self) -> bool:
        with self._lock:
            if self._inflight >= self.MIN_LIMIT and self._ema_us > 0:
                # queueing behind `inflight` others plus its own service
                expected_done = (self._inflight + 1) * self._ema_us
                if expected_done > self._timeout_us:
                    return False
            self._inflight += 1
            return True

    def on_responded(self, latency_us, failed):
        # failures count too: during sustained overload every request
        # dies at the timeout, and skipping them would freeze the EMA at
        # the last healthy value — exactly when shedding matters most.
        # A timeout corpse's latency (~the timeout) pushes the estimate
        # up; recovery pulls it back down through later successes.
        with self._lock:
            self._inflight -= 1
            if latency_us > 0:
                if self._ema_us == 0:
                    self._ema_us = latency_us
                else:
                    self._ema_us += self.EMA_ALPHA * (latency_us - self._ema_us)

    @property
    def max_concurrency(self):
        with self._lock:
            if self._ema_us <= 0:
                return 1 << 30
            return max(self.MIN_LIMIT,
                       int(self._timeout_us / self._ema_us))
