"""Concurrency limiters (brpc/concurrency_limiter.h:29;
policy/auto_concurrency_limiter.cpp).

``constant``: fixed max in-flight. ``auto``: gradient/Vegas-style — track
the best observed latency; if current latency inflates, shrink the limit,
else grow it (the reference's AutoConcurrencyLimiter in miniature).
``timeout``: admit only while expected queueing delay fits the budget.

The Server drives whichever limiter its ``max_concurrency`` spec names
from BOTH dispatch paths (classic process_request and the turbo
process_request_fast lane) through ``on_requested``/``on_responded`` —
see Server.on_request_start/on_request_end; per-method limits ride
``ServerOptions.method_max_concurrency``.
"""

from __future__ import annotations

import threading
import time
from typing import Optional


class ConcurrencyLimiter:
    """``cost`` (ISSUE 14): weighted slots — the admission cost model
    charges heavy requests more than one slot (request bytes +
    expected-latency bucket, rpc/admission.CostModel), so weighted
    inflight tracks real pressure. The default cost of 1.0 is exactly
    the PR 10 slot; a release must pass the SAME cost its admission
    charged (the Server threads it through the request lifecycle)."""

    def on_requested(self, cost: float = 1.0) -> bool:
        """False = reject with ELIMIT."""
        raise NotImplementedError

    def on_responded(self, latency_us: float, failed: bool,
                     cost: float = 1.0) -> None:
        raise NotImplementedError

    @property
    def max_concurrency(self) -> int:
        raise NotImplementedError


class ConstantLimiter(ConcurrencyLimiter):
    def __init__(self, limit: int):
        self._limit = limit
        self._inflight = 0.0
        self._lock = threading.Lock()

    def on_requested(self, cost: float = 1.0) -> bool:
        # admit while weighted inflight sits below the limit: a heavy
        # request admitted at the boundary may overshoot by its own
        # cost (weighted-semaphore semantics — it can never be starved
        # by lighter traffic), but everything behind it then waits for
        # the weighted release
        with self._lock:
            if self._inflight >= self._limit:
                return False
            self._inflight += cost
            return True

    def on_responded(self, latency_us, failed, cost: float = 1.0):
        with self._lock:
            self._inflight = max(0.0, self._inflight - cost)

    @property
    def inflight(self) -> float:
        return self._inflight

    @property
    def max_concurrency(self):
        return self._limit


class AutoLimiter(ConcurrencyLimiter):
    """Vegas/gradient adaptive limit. Two hardenings over the naive
    sample-count window (both found by driving it from the server's
    dispatch paths under fault):

    * windows close on TIME as well as count — a shrunken limit under
      light traffic would otherwise never collect SAMPLE_WINDOW
      observations again and stay pinned small forever;
    * failed responses release the in-flight slot but feed no latency
      (an ELIMIT/exception corpse's near-zero latency would drag the
      average DOWN exactly while the node is sick, growing the limit
      into the overload).
    """

    MIN_LIMIT = 2
    MAX_LIMIT = 4096
    SAMPLE_WINDOW = 100
    MIN_WINDOW_SAMPLES = 8      # time-closed windows need this many
    WINDOW_S = 1.0
    INFLATE_TOLERANCE = 1.5     # latency may inflate this much before shrink
    GROW = 1.1
    SHRINK = 0.8

    def __init__(self, initial: int = 32, min_concurrency: int = 4,
                 max_concurrency: int = 4096):
        self.min_concurrency = max(self.MIN_LIMIT, int(min_concurrency))
        self.max_limit = min(self.MAX_LIMIT, max(int(max_concurrency),
                                                 self.min_concurrency))
        self._limit = float(min(max(initial, self.min_concurrency),
                                self.max_limit))
        self._inflight = 0.0
        self._lock = threading.Lock()
        self._best_latency = float("inf")
        self._lat_sum = 0.0
        self._lat_n = 0
        self._win_start = time.monotonic()

    def on_requested(self, cost: float = 1.0) -> bool:
        with self._lock:
            if self._inflight >= int(self._limit):
                return False
            self._inflight += cost
            return True

    def on_responded(self, latency_us, failed, cost: float = 1.0):
        with self._lock:
            self._inflight = max(0.0, self._inflight - cost)
            if failed:
                return
            self._lat_sum += latency_us
            self._lat_n += 1
            if self._lat_n < self.SAMPLE_WINDOW:
                now = time.monotonic()
                if (now - self._win_start < self.WINDOW_S
                        or self._lat_n < self.MIN_WINDOW_SAMPLES):
                    return
            self._close_window_locked()

    def _close_window_locked(self) -> None:
        avg = self._lat_sum / self._lat_n
        self._lat_sum = 0.0
        self._lat_n = 0
        self._win_start = time.monotonic()
        self._best_latency = min(self._best_latency, avg)
        if avg > self._best_latency * self.INFLATE_TOLERANCE:
            self._limit = max(self.min_concurrency, self._limit * self.SHRINK)
            # forgive the past: latency regimes change
            self._best_latency = min(avg, self._best_latency * 1.1)
        else:
            self._limit = min(self.max_limit, self._limit * self.GROW)

    @property
    def inflight(self) -> float:
        return self._inflight

    @property
    def max_concurrency(self):
        return int(self._limit)


def new_limiter(spec) -> Optional[ConcurrencyLimiter]:
    """spec: None | int | 'constant:N' | 'auto[:initial[:min[:max]]]'
    | 'timeout:MS' (the AdaptiveMaxConcurrency vocabulary —
    ``-max_concurrency auto`` in the reference)."""
    if spec is None:
        return None
    if isinstance(spec, int):
        return ConstantLimiter(spec)
    # NOTE deliberately no limiter-INSTANCE passthrough: the Server
    # re-runs this parser in its postfork re-arm so a forked shard
    # gets fresh inflight counts and locks — a shared instance would
    # hand every shard the parent's mid-flight state (leaked admission
    # slots, possibly a lock held at fork time)
    if isinstance(spec, str):
        if spec == "auto" or spec.startswith("auto:"):
            args = [int(p) for p in spec.split(":")[1:]]
            kw = {}
            if len(args) >= 1:
                kw["initial"] = args[0]
            if len(args) >= 2:
                kw["min_concurrency"] = args[1]
            if len(args) >= 3:
                kw["max_concurrency"] = args[2]
            return AutoLimiter(**kw)
        if spec.startswith("constant:"):
            return ConstantLimiter(int(spec.split(":", 1)[1]))
        if spec.startswith("timeout:"):
            return TimeoutLimiter(float(spec.split(":", 1)[1]))
        if spec.isdigit():
            return ConstantLimiter(int(spec))
    raise ValueError(f"bad concurrency limiter spec {spec!r}")


class TimeoutLimiter(ConcurrencyLimiter):
    """Timeout-aware limiter (policy/timeout_concurrency_limiter.cpp):
    admit a request only while the expected queueing delay —
    in-flight x EMA latency — still fits inside the timeout budget, so
    requests that would certainly time out in the queue are shed at the
    door instead of wasting a slot."""

    MIN_LIMIT = 2
    EMA_ALPHA = 0.2

    def __init__(self, timeout_ms: float):
        self._timeout_us = float(timeout_ms) * 1e3
        self._ema_us = 0.0
        self._inflight = 0.0
        self._lock = threading.Lock()

    def on_requested(self, cost: float = 1.0) -> bool:
        with self._lock:
            if self._inflight >= self.MIN_LIMIT and self._ema_us > 0:
                # queueing behind `inflight` weighted others plus its
                # own weighted service
                expected_done = (self._inflight + cost) * self._ema_us
                if expected_done > self._timeout_us:
                    return False
            self._inflight += cost
            return True

    def on_responded(self, latency_us, failed, cost: float = 1.0):
        # failures count too: during sustained overload every request
        # dies at the timeout, and skipping them would freeze the EMA at
        # the last healthy value — exactly when shedding matters most.
        # A timeout corpse's latency (~the timeout) pushes the estimate
        # up; recovery pulls it back down through later successes.
        with self._lock:
            self._inflight = max(0.0, self._inflight - cost)
            if latency_us > 0:
                if self._ema_us == 0:
                    self._ema_us = latency_us
                else:
                    self._ema_us += self.EMA_ALPHA * (latency_us - self._ema_us)

    @property
    def max_concurrency(self):
        with self._lock:
            if self._ema_us <= 0:
                return 1 << 30
            return max(self.MIN_LIMIT,
                       int(self._timeout_us / self._ema_us))
