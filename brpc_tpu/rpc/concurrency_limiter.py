"""Concurrency limiters (brpc/concurrency_limiter.h:29;
policy/auto_concurrency_limiter.cpp).

``constant``: fixed max in-flight. ``auto``: gradient/Vegas-style — track
the best observed latency; if current latency inflates, shrink the limit,
else grow it (the reference's AutoConcurrencyLimiter in miniature).
"""

from __future__ import annotations

import threading
import time
from typing import Optional


class ConcurrencyLimiter:
    def on_requested(self) -> bool:
        """False = reject with ELIMIT."""
        raise NotImplementedError

    def on_responded(self, latency_us: float, failed: bool) -> None:
        raise NotImplementedError

    @property
    def max_concurrency(self) -> int:
        raise NotImplementedError


class ConstantLimiter(ConcurrencyLimiter):
    def __init__(self, limit: int):
        self._limit = limit
        self._inflight = 0
        self._lock = threading.Lock()

    def on_requested(self) -> bool:
        with self._lock:
            if self._inflight >= self._limit:
                return False
            self._inflight += 1
            return True

    def on_responded(self, latency_us, failed):
        with self._lock:
            self._inflight -= 1

    @property
    def max_concurrency(self):
        return self._limit


class AutoLimiter(ConcurrencyLimiter):
    MIN_LIMIT = 4
    MAX_LIMIT = 4096
    SAMPLE_WINDOW = 100
    INFLATE_TOLERANCE = 1.5     # latency may inflate this much before shrink
    GROW = 1.1
    SHRINK = 0.8

    def __init__(self, initial: int = 32):
        self._limit = float(initial)
        self._inflight = 0
        self._lock = threading.Lock()
        self._best_latency = float("inf")
        self._lat_sum = 0.0
        self._lat_n = 0

    def on_requested(self) -> bool:
        with self._lock:
            if self._inflight >= int(self._limit):
                return False
            self._inflight += 1
            return True

    def on_responded(self, latency_us, failed):
        with self._lock:
            self._inflight -= 1
            if failed:
                return
            self._lat_sum += latency_us
            self._lat_n += 1
            if self._lat_n < self.SAMPLE_WINDOW:
                return
            avg = self._lat_sum / self._lat_n
            self._lat_sum = 0.0
            self._lat_n = 0
            self._best_latency = min(self._best_latency, avg)
            if avg > self._best_latency * self.INFLATE_TOLERANCE:
                self._limit = max(self.MIN_LIMIT, self._limit * self.SHRINK)
                # forgive the past: latency regimes change
                self._best_latency = min(avg, self._best_latency * 1.1)
            else:
                self._limit = min(self.MAX_LIMIT, self._limit * self.GROW)

    @property
    def max_concurrency(self):
        return int(self._limit)


def new_limiter(spec) -> Optional[ConcurrencyLimiter]:
    """spec: None | int | 'constant:N' | 'auto' (AdaptiveMaxConcurrency)."""
    if spec is None:
        return None
    if isinstance(spec, int):
        return ConstantLimiter(spec)
    if isinstance(spec, str):
        if spec == "auto":
            return AutoLimiter()
        if spec.startswith("constant:"):
            return ConstantLimiter(int(spec.split(":", 1)[1]))
        if spec.isdigit():
            return ConstantLimiter(int(spec))
    raise ValueError(f"bad concurrency limiter spec {spec!r}")
