"""Compression registry (brpc/compress.{h,cpp} + policy/gzip_compress.cpp,
snappy_compress.cpp). Payload compression by numeric type carried in
RpcMeta.compress_type; both sides look the codec up here.

Builtin: 0=none, 1=gzip, 2=zlib, 3=snappy (butil/snappy_codec — native
C++ with a bit-identical pure-Python fallback, like the reference's
vendored snappy). More codecs plug in via register_compressor."""

from __future__ import annotations

import gzip
import zlib
from typing import Callable, Dict, Optional, Tuple

from brpc_tpu.butil import snappy_codec

COMPRESS_NONE = 0
COMPRESS_GZIP = 1
COMPRESS_ZLIB = 2
COMPRESS_SNAPPY = 3

_codecs: Dict[int, Tuple[Callable[[bytes], bytes], Callable[[bytes], bytes], str]] = {
    COMPRESS_GZIP: (lambda b: gzip.compress(b, 6), gzip.decompress, "gzip"),
    COMPRESS_ZLIB: (zlib.compress, zlib.decompress, "zlib"),
    COMPRESS_SNAPPY: (snappy_codec.compress_auto, snappy_codec.decompress_auto,
                      "snappy"),
}


def register_compressor(ctype: int, compress: Callable, decompress: Callable,
                        name: str) -> None:
    _codecs[ctype] = (compress, decompress, name)


def compress(data: bytes, ctype: int) -> bytes:
    if ctype == COMPRESS_NONE or not data:
        return data
    codec = _codecs.get(ctype)
    if codec is None:
        raise ValueError(f"unknown compress_type {ctype}")
    return codec[0](data)


def decompress(data: bytes, ctype: int) -> bytes:
    if ctype == COMPRESS_NONE or not data:
        return data
    codec = _codecs.get(ctype)
    if codec is None:
        raise ValueError(f"unknown compress_type {ctype}")
    return codec[1](data)


def compressor_name(ctype: int) -> str:
    if ctype == COMPRESS_NONE:
        return "none"
    c = _codecs.get(ctype)
    return c[2] if c else f"unknown({ctype})"
