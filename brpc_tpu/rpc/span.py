"""rpcz tracing: per-RPC spans through a bounded collector
(brpc/span.h:47, bvar/collector.* — SURVEY.md §5).

Spans are cheap dataclass records annotated at each stage and kept in a
ring buffer, dumped by /rpcz. Setting the ``rpcz_dir`` flag additionally
persists finished spans to a bounded on-disk store (the reference's
leveldb SpanDB, span.cpp:308, as rotating JSON-lines files):
/rpcz?history=1 reads back spans that have aged out of the ring. Trace
ids propagate in RpcMeta (trace_id/span_id/parent_span_id fields), so
multi-hop call trees link up.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, List, Optional, Tuple

from brpc_tpu.butil.fast_rand import fast_rand
from brpc_tpu.butil.flags import flag


# monotonic->wall-clock anchor, computed once per process: stage stamps
# use the monotonic clock (immune to NTP steps mid-RPC), but cross-process
# trace assembly needs a shared timeline — to_dict emits base_real_us =
# start_us + this offset (the reference's span.h base_real_us plays the
# same role for its cpuwide stamps)
_REAL_OFFSET_US = time.time_ns() // 1000 - time.monotonic_ns() // 1000


@dataclass
class Span:
    trace_id: int
    span_id: int
    parent_span_id: int = 0
    side: str = "server"            # server | client
    service: str = ""
    method: str = ""
    remote_side: str = ""
    start_us: int = 0
    end_us: int = 0
    error_code: int = 0
    log_id: int = 0
    request_size: int = 0
    response_size: int = 0
    # ---- stage timeline (monotonic us; 0 = stage never reached). The
    # reference records the same waypoints in span.h (received_us,
    # start_parse_us, start_callback_us, sent_us): they are what turns
    # "this RPC was slow" into "it queued / it computed / it flushed".
    # Server side:
    received_us: int = 0        # frame cut (RpcMessage.arrival_ns)
    dispatch_us: int = 0        # dispatch context entered (queue exit)
    parse_done_us: int = 0      # request payload decoded (server) /
    #                             response payload decoded (client)
    handler_start_us: int = 0   # user handler entered
    handler_end_us: int = 0     # user handler returned/raised
    serialized_us: int = 0      # response frame packed
    flushed_us: int = 0         # response write completed (on_done)
    # Client side:
    write_done_us: int = 0      # request write completed (on_done)
    first_byte_us: int = 0      # response frame seen by the client
    annotations: List[Tuple[int, str]] = field(default_factory=list)
    # response-flush delegation latch (server side): when the response
    # write's completion callback owns the flush stamp, finish_span may
    # run before OR after it — exactly one of them submits the span
    _flush_lock: threading.Lock = field(default_factory=threading.Lock,
                                        repr=False, compare=False)
    _await_flush: bool = field(default=False, repr=False, compare=False)
    _finish_ready: bool = field(default=False, repr=False, compare=False)

    def annotate(self, text: str) -> None:
        self.annotations.append((time.monotonic_ns() // 1000, text))

    @property
    def latency_us(self) -> int:
        return max(0, self.end_us - self.start_us)

    def stage_breakdown(self) -> Tuple[int, int, int]:
        """(queue_us, handle_us, write_us) — the three-way attribution
        tail debugging needs. Server: arrival->handler (queueing +
        parse), handler, handler->flush (serialize + write). Client:
        issue->write-done, write-done->first-response-byte (network +
        server residence), first-byte->completion. Device transfers
        reuse the client shape with the lane waypoints mapped onto it
        (write_done_us = descriptor encoded, first_byte_us = frame
        flushed to transport, end_us = peer ack) so the triple reads
        (stage_us, wire_us, ack_us) — see to_dict's aliases. Sums to
        ~latency_us; a span that never reached its handler puts
        everything in queue_us."""
        if self.side == "server":
            base = self.received_us or self.start_us
            mid0, mid1 = self.handler_start_us, self.handler_end_us
            tail = self.flushed_us or self.end_us
        else:
            base = self.start_us
            mid0, mid1 = self.write_done_us, self.first_byte_us
            tail = self.end_us
        if mid0 and mid1:
            return (max(0, mid0 - base), max(0, mid1 - mid0),
                    max(0, tail - mid1))
        return (max(0, tail - base), 0, 0)

    def to_dict(self) -> dict:
        queue_us, handle_us, write_us = self.stage_breakdown()
        d = {
            "trace_id": f"{self.trace_id:016x}",
            "span_id": f"{self.span_id:016x}",
            "parent_span_id": f"{self.parent_span_id:016x}",
            "side": self.side,
            "service": self.service,
            "method": self.method,
            "remote_side": self.remote_side,
            "latency_us": self.latency_us,
            "error_code": self.error_code,
            "log_id": self.log_id,
            "request_size": self.request_size,
            "response_size": self.response_size,
            # timeline: start_us is process-monotonic (stage stamps share
            # its clock); base_real_us anchors it on the wall clock so
            # stores from different processes assemble onto one axis
            "pid": os.getpid(),
            "start_us": self.start_us,
            "end_us": self.end_us,
            "base_real_us": self.start_us + _REAL_OFFSET_US,
            "received_us": self.received_us,
            "dispatch_us": self.dispatch_us,
            "parse_done_us": self.parse_done_us,
            "handler_start_us": self.handler_start_us,
            "handler_end_us": self.handler_end_us,
            "serialized_us": self.serialized_us,
            "flushed_us": self.flushed_us,
            "write_done_us": self.write_done_us,
            "first_byte_us": self.first_byte_us,
            "queue_us": queue_us,
            "handle_us": handle_us,
            "write_us": write_us,
            "annotations": [
                {"us": us, "text": t} for us, t in self.annotations],
        }
        if self.side == "device":
            # the device lane's waypoint names (transport/device_stats):
            # host staging + descriptor encode, lane-enqueue/credit wait
            # + pump flush, wire + peer recv + ack return
            d["stage_us"] = queue_us
            d["wire_us"] = handle_us
            d["ack_us"] = write_us
        elif self.side == "serving":
            # the serving lane's waypoint names (serving/serving_stats):
            # submit->admit (write_done_us), admit->prefill-done
            # (first_byte_us), prefill-done->decode-done (serialized_us),
            # decode-done->emitted (end_us). Telescoping fallbacks: a
            # stage never reached contributes 0 and its time lands in
            # the previous stage, so the four ALWAYS sum to latency_us.
            a = self.write_done_us or self.end_us
            p = self.first_byte_us or a
            f = self.serialized_us or p
            d["queue_us"] = max(0, a - self.start_us)
            d["prefill_us"] = max(0, p - a)
            d["decode_us"] = max(0, f - p)
            d["emit_us"] = max(0, self.end_us - f)
        return d


class SpanCollector:
    """Bounded ring; submission is O(1) and never blocks the RPC path
    (the reference bounds collection cost via bvar::Collector's
    per-second budget — a ring buffer gives the same property)."""

    def __init__(self, capacity: Optional[int] = None):
        self._lock = threading.Lock()
        self._capacity = capacity
        self._ring: Deque[Span] = deque(maxlen=capacity or flag("rpcz_max_spans"))

    def submit(self, span: Span) -> None:
        if not flag("rpcz_enabled"):
            return
        with self._lock:
            # honor runtime /flags mutation of rpcz_max_spans: resize the
            # ring when the flag moved (constructor-captured maxlen would
            # make the advertised knob a no-op)
            want = self._capacity or flag("rpcz_max_spans")
            if want != self._ring.maxlen:
                self._ring = deque(self._ring, maxlen=want)
            self._ring.append(span)

    def recent(self, n: int = 100) -> List[Span]:
        with self._lock:
            return list(self._ring)[-n:]

    def find_trace(self, trace_id) -> List[Span]:
        """``trace_id``: an int, or a collection of candidate ints (the
        /rpcz handler accepts both hex and decimal spellings of an id
        and matches either reading)."""
        ids = {trace_id} if isinstance(trace_id, int) else set(trace_id)
        with self._lock:
            return [s for s in self._ring if s.trace_id in ids]

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()


class SpanStore:
    """Bounded on-disk persistence: JSON-lines, rotated once at
    rpcz_db_max_bytes (current + one aged file ≈ the leveldb SpanDB's
    bounded footprint). finish_span runs for EVERY rpc, so writes buffer
    in memory and hit disk in batches (every _FLUSH_EVERY lines or
    _FLUSH_S seconds), never a per-RPC syscall."""

    FILE = "rpcz_spans.jsonl"
    _FLUSH_EVERY = 32
    _FLUSH_S = 0.5

    def __init__(self):
        self._lock = threading.Lock()
        self._fh = None
        self._dir = None
        self._buf: List[str] = []
        self._last_flush = 0.0

    def _path(self, old: bool = False) -> str:
        return os.path.join(self._dir, self.FILE + (".1" if old else ""))

    def _ensure_open(self, dirpath: str):
        if self._fh is not None and self._dir == dirpath:
            return
        if self._fh is not None:
            self._fh.close()
        self._dir = dirpath
        os.makedirs(dirpath, exist_ok=True)
        self._fh = open(self._path(), "a", encoding="utf-8")

    def _flush_locked(self, dirpath: str) -> None:
        self._ensure_open(dirpath)
        self._fh.write("".join(self._buf))
        self._fh.flush()
        self._buf.clear()
        self._last_flush = time.monotonic()
        if self._fh.tell() >= int(flag("rpcz_db_max_bytes")):
            self._fh.close()
            self._fh = None
            os.replace(self._path(), self._path(old=True))

    def write(self, span: "Span") -> None:
        dirpath = flag("rpcz_dir")
        with self._lock:
            if not dirpath:
                # flag cleared at runtime: drop buffered lines and the
                # handle (an open fd would pin the old directory)
                if self._fh is not None:
                    try:
                        self._fh.close()
                    except OSError:
                        pass
                    self._fh = None
                self._buf.clear()
                return
            self._buf.append(json.dumps(span.to_dict()) + "\n")
            if (len(self._buf) < self._FLUSH_EVERY
                    and time.monotonic() - self._last_flush < self._FLUSH_S):
                return
            try:
                self._flush_locked(dirpath)
            except OSError:
                self._buf.clear()   # persistence must never fail the RPC

    def read(self, n: int = 100, trace_id=None) -> List[dict]:
        """``trace_id``: None (all spans), an int, or a collection of
        candidate ints (matched against any)."""
        dirpath = flag("rpcz_dir")
        if not dirpath or n <= 0:
            return []
        # flush pending lines under the lock so history is current, but
        # SCAN outside it — parsing up to 2x rpcz_db_max_bytes of JSON
        # under the write lock would stall every RPC's finish_span. A
        # concurrent rotation mid-scan costs at most a transient miss on
        # this diagnostic page (os.replace is atomic; open fds survive).
        with self._lock:
            if self._buf:
                try:
                    self._flush_locked(dirpath)
                except OSError:
                    self._buf.clear()
        ids = None
        if trace_id is not None:
            ids = {trace_id} if isinstance(trace_id, int) else set(trace_id)
        # bounded ring while scanning — never materialize all lines
        rows: Deque[dict] = deque(maxlen=n)
        for old in (True, False):       # aged file first: oldest→newest
            try:
                with open(os.path.join(dirpath,
                                       self.FILE + (".1" if old else "")),
                          encoding="utf-8") as f:
                    for line in f:
                        try:
                            d = json.loads(line)
                        except ValueError:
                            continue
                        if ids is None or \
                                int(d.get("trace_id", "0"), 16) in ids:
                            rows.append(d)
            except OSError:
                continue
        return list(rows)


    def flush(self) -> None:
        """Force buffered lines to disk (server stop / process exit —
        the last spans before a shutdown are usually the interesting
        ones)."""
        dirpath = flag("rpcz_dir")
        if not dirpath:
            return
        with self._lock:
            if self._buf:
                try:
                    self._flush_locked(dirpath)
                except OSError:
                    self._buf.clear()


global_store = SpanStore()
global_collector = SpanCollector()

import atexit  # noqa: E402  (registration belongs with the store)

atexit.register(global_store.flush)


def _postfork_reset() -> None:
    """Fork hygiene: the store's open fh shares one file offset with
    the parent (interleaved writes would shred the JSONL), its lock
    may be held by a dead thread, and buffered/ringed spans describe
    parent-side RPCs. A shard starts with an empty rpcz state and its
    own store, opened lazily at its own rpcz_dir."""
    global_store._lock = threading.Lock()
    fh, global_store._fh = global_store._fh, None
    global_store._dir = None
    global_store._buf = []
    if fh is not None:
        try:
            fh.close()     # only the child's dup of the descriptor
        except Exception:
            pass
    global_collector._lock = threading.Lock()
    global_collector._ring.clear()


from brpc_tpu.butil import postfork as _postfork  # noqa: E402
#   (registration ships with the store it resets)

_postfork.register("rpc.span", _postfork_reset)


def _span_census() -> dict:
    """Resource census: what rpcz holds in memory — the bounded ring
    plus the store's not-yet-flushed line buffer."""
    with global_store._lock:
        buffered = sum(len(s) for s in global_store._buf)
    with global_collector._lock:
        ring = len(global_collector._ring)
    return {"count": ring, "bytes": buffered,
            "ring_capacity": global_collector._ring.maxlen}


from brpc_tpu.butil import resource_census as _census  # noqa: E402
#   (census registration ships with the store it measures)

_census.register("span_store", _span_census)


def new_trace_id() -> int:
    return fast_rand() or 1


def start_server_span(cntl, service: str, method: str) -> Span:
    """CreateServerSpan (span.cpp:149): trace context from the request
    meta, or a fresh trace."""
    trace_id = cntl.trace_id or new_trace_id()
    span = Span(
        trace_id=trace_id,
        span_id=new_trace_id(),
        parent_span_id=cntl.span_id,
        side="server",
        service=service,
        method=method,
        remote_side=str(cntl.remote_side) if cntl.remote_side else "",
        start_us=time.monotonic_ns() // 1000,
        log_id=cntl.log_id,
    )
    cntl.trace_id = trace_id       # propagate to downstream client calls
    cntl.span_id = span.span_id
    return span


def start_client_span(cntl, service: str, method: str) -> Span:
    trace_id = cntl.trace_id or new_trace_id()
    span = Span(
        trace_id=trace_id,
        span_id=new_trace_id(),
        parent_span_id=cntl.span_id,
        side="client",
        service=service,
        method=method,
        start_us=time.monotonic_ns() // 1000,
        log_id=cntl.log_id,
    )
    cntl.trace_id = trace_id
    cntl.span_id = span.span_id
    return span


def start_attempt_span(parent: Span, service: str, method: str,
                       attempt: int, backend: str,
                       backup: bool = False) -> Span:
    """A per-attempt child of a client call span: retries and backup
    requests fan the one logical call out over several backends, and a
    single client span collapses that into an undifferentiated blob.
    The attempt span carries the 1-based attempt index and the selected
    backend endpoint (remote_side + a greppable annotation). The
    channel submits the set only for multi-attempt calls — see
    channel._finish_call_spans."""
    span = Span(
        trace_id=parent.trace_id,
        span_id=new_trace_id(),
        parent_span_id=parent.span_id,
        side="client",
        service=service,
        method=method,
        remote_side=backend,
        start_us=time.monotonic_ns() // 1000,
        log_id=parent.log_id,
    )
    span.annotate(f"attempt={attempt} backend={backend}"
                  + (" backup" if backup else ""))
    return span


def start_device_span(parent: Span, peer: str, lane: str) -> Span:
    """A device-transfer child of the owning RPC span: the lane's
    stage-resolved waypoints (host-stage/encode, credit-wait + pump
    flush, wire + peer ack) ride the client-shaped stamp slots —
    write_done_us = encoded, first_byte_us = flushed, end_us = acked —
    so stage_breakdown yields (stage_us, wire_us, ack_us) summing to
    the transfer latency (see Span.to_dict's device aliases). The
    tracker (transport/device_stats.BatchTracker) stamps and submits;
    trace/parent inheritance keeps the transfer inside the call tree
    the serving controller / client channel started."""
    span = Span(
        trace_id=parent.trace_id,
        span_id=new_trace_id(),
        parent_span_id=parent.span_id,
        side="device",
        service="device",
        method=lane,
        remote_side=peer,
        start_us=time.monotonic_ns() // 1000,
        log_id=parent.log_id,
    )
    span.annotate(f"device transfer peer={peer} lane={lane}")
    return span


def start_serving_span(cntl, service: str, method: str) -> Span:
    """A token-generation child of the owning RPC span: the serving
    lane's stage-resolved waypoints (queue, prefill, decode, emit) ride
    the client-shaped stamp slots — write_done_us = admitted,
    first_byte_us = prefill done, serialized_us = decode done, end_us =
    emitted — so to_dict yields (queue_us, prefill_us, decode_us,
    emit_us) summing to the stream latency (see the serving aliases).
    The tracker (serving/serving_stats.GenTracker) stamps and submits;
    trace/parent inheritance through the serving controller (whose
    trace_id/span_id start_server_span set) keeps the generation inside
    the call tree — the start_device_span idiom for the token lane."""
    span = Span(
        trace_id=getattr(cntl, "trace_id", 0) or new_trace_id(),
        span_id=new_trace_id(),
        parent_span_id=getattr(cntl, "span_id", 0) or 0,
        side="serving",
        service=service,
        method=method,
        remote_side=str(cntl.remote_side)
        if getattr(cntl, "remote_side", None) else "",
        start_us=time.monotonic_ns() // 1000,
        log_id=getattr(cntl, "log_id", 0) or 0,
    )
    span.annotate(f"generation {service}.{method}")
    return span


def submit_device_recv_span(parent: Span, dr: dict) -> None:
    """The receiving half of a device transfer (take_device_payload:
    pull DMA / staged device_put + recv-pool admission) as a finished
    child span of the owning RPC span. ``dr`` is the socket's
    ``last_device_take`` record (peer/lane/recv_us/nbytes/t_us) —
    one helper so the server- and client-side parse paths cannot
    drift."""
    span = start_device_span(parent, dr.get("peer", ""),
                             dr.get("lane", ""))
    span.start_us = dr.get("t_us") or span.start_us
    span.end_us = span.start_us + int(dr.get("recv_us", 0))
    span.request_size = dr.get("nbytes", 0)
    span.annotate(f"device-recv recv_us={dr.get('recv_us')} "
                  f"nbytes={dr.get('nbytes')}")
    _submit_span(span)


def submit_span(span: Span) -> None:
    """Submit an externally-finished span (attempt children whose
    end_us/error_code the channel stamped itself)."""
    _submit_span(span)


def expect_flush(span: Span) -> None:
    """Arm the flush-delegation latch: the response write's completion
    callback (mark_flushed) owns the flushed_us stamp, and whichever of
    finish_span / mark_flushed runs LAST submits the span — so the
    stored timeline includes the real write completion even when the
    conn blocks (a chaos ``delay`` fault, a saturated peer) and the
    dispatch context moves on."""
    span._await_flush = True


def mark_flushed(span: Span, err=None) -> None:
    """The write on_done half of the latch (stamps only on success —
    a failed write has no flush time)."""
    submit = False
    with span._flush_lock:
        if err is None and not span.flushed_us:
            span.flushed_us = time.monotonic_ns() // 1000
        span._await_flush = False
        if span._finish_ready:
            span._finish_ready = False
            submit = True
            if span.end_us < span.flushed_us:
                span.end_us = span.flushed_us
    if submit:
        _submit_span(span)


def finish_span(span: Span, cntl) -> None:
    span.end_us = time.monotonic_ns() // 1000
    span.error_code = cntl.error_code
    if cntl.remote_side and not span.remote_side:
        span.remote_side = str(cntl.remote_side)
    if span._await_flush:
        with span._flush_lock:
            if span._await_flush:
                # the response write hasn't completed: mark_flushed
                # submits when it does (end_us then covers the flush)
                span._finish_ready = True
                return
    _submit_span(span)


def _submit_span(span: Span) -> None:
    global_collector.submit(span)
    if flag("rpcz_enabled"):
        global_store.write(span)
