"""rpcz tracing: per-RPC spans through a bounded collector
(brpc/span.h:47, bvar/collector.* — SURVEY.md §5).

Spans are cheap dataclass records annotated at each stage and kept in a
ring buffer, dumped by /rpcz. Setting the ``rpcz_dir`` flag additionally
persists finished spans to a bounded on-disk store (the reference's
leveldb SpanDB, span.cpp:308, as rotating JSON-lines files):
/rpcz?history=1 reads back spans that have aged out of the ring. Trace
ids propagate in RpcMeta (trace_id/span_id/parent_span_id fields), so
multi-hop call trees link up.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, List, Optional, Tuple

from brpc_tpu.butil.fast_rand import fast_rand
from brpc_tpu.butil.flags import flag


@dataclass
class Span:
    trace_id: int
    span_id: int
    parent_span_id: int = 0
    side: str = "server"            # server | client
    service: str = ""
    method: str = ""
    remote_side: str = ""
    start_us: int = 0
    end_us: int = 0
    error_code: int = 0
    log_id: int = 0
    request_size: int = 0
    response_size: int = 0
    annotations: List[Tuple[int, str]] = field(default_factory=list)

    def annotate(self, text: str) -> None:
        self.annotations.append((time.monotonic_ns() // 1000, text))

    @property
    def latency_us(self) -> int:
        return max(0, self.end_us - self.start_us)

    def to_dict(self) -> dict:
        return {
            "trace_id": f"{self.trace_id:016x}",
            "span_id": f"{self.span_id:016x}",
            "parent_span_id": f"{self.parent_span_id:016x}",
            "side": self.side,
            "service": self.service,
            "method": self.method,
            "remote_side": self.remote_side,
            "latency_us": self.latency_us,
            "error_code": self.error_code,
            "log_id": self.log_id,
            "request_size": self.request_size,
            "response_size": self.response_size,
            "annotations": [
                {"us": us, "text": t} for us, t in self.annotations],
        }


class SpanCollector:
    """Bounded ring; submission is O(1) and never blocks the RPC path
    (the reference bounds collection cost via bvar::Collector's
    per-second budget — a ring buffer gives the same property)."""

    def __init__(self, capacity: Optional[int] = None):
        self._lock = threading.Lock()
        self._capacity = capacity
        self._ring: Deque[Span] = deque(maxlen=capacity or flag("rpcz_max_spans"))

    def submit(self, span: Span) -> None:
        if not flag("rpcz_enabled"):
            return
        with self._lock:
            # honor runtime /flags mutation of rpcz_max_spans: resize the
            # ring when the flag moved (constructor-captured maxlen would
            # make the advertised knob a no-op)
            want = self._capacity or flag("rpcz_max_spans")
            if want != self._ring.maxlen:
                self._ring = deque(self._ring, maxlen=want)
            self._ring.append(span)

    def recent(self, n: int = 100) -> List[Span]:
        with self._lock:
            return list(self._ring)[-n:]

    def find_trace(self, trace_id: int) -> List[Span]:
        with self._lock:
            return [s for s in self._ring if s.trace_id == trace_id]

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()


class SpanStore:
    """Bounded on-disk persistence: JSON-lines, rotated once at
    rpcz_db_max_bytes (current + one aged file ≈ the leveldb SpanDB's
    bounded footprint). finish_span runs for EVERY rpc, so writes buffer
    in memory and hit disk in batches (every _FLUSH_EVERY lines or
    _FLUSH_S seconds), never a per-RPC syscall."""

    FILE = "rpcz_spans.jsonl"
    _FLUSH_EVERY = 32
    _FLUSH_S = 0.5

    def __init__(self):
        self._lock = threading.Lock()
        self._fh = None
        self._dir = None
        self._buf: List[str] = []
        self._last_flush = 0.0

    def _path(self, old: bool = False) -> str:
        return os.path.join(self._dir, self.FILE + (".1" if old else ""))

    def _ensure_open(self, dirpath: str):
        if self._fh is not None and self._dir == dirpath:
            return
        if self._fh is not None:
            self._fh.close()
        self._dir = dirpath
        os.makedirs(dirpath, exist_ok=True)
        self._fh = open(self._path(), "a", encoding="utf-8")

    def _flush_locked(self, dirpath: str) -> None:
        self._ensure_open(dirpath)
        self._fh.write("".join(self._buf))
        self._fh.flush()
        self._buf.clear()
        self._last_flush = time.monotonic()
        if self._fh.tell() >= int(flag("rpcz_db_max_bytes")):
            self._fh.close()
            self._fh = None
            os.replace(self._path(), self._path(old=True))

    def write(self, span: "Span") -> None:
        dirpath = flag("rpcz_dir")
        with self._lock:
            if not dirpath:
                # flag cleared at runtime: drop buffered lines and the
                # handle (an open fd would pin the old directory)
                if self._fh is not None:
                    try:
                        self._fh.close()
                    except OSError:
                        pass
                    self._fh = None
                self._buf.clear()
                return
            self._buf.append(json.dumps(span.to_dict()) + "\n")
            if (len(self._buf) < self._FLUSH_EVERY
                    and time.monotonic() - self._last_flush < self._FLUSH_S):
                return
            try:
                self._flush_locked(dirpath)
            except OSError:
                self._buf.clear()   # persistence must never fail the RPC

    def read(self, n: int = 100,
             trace_id: Optional[int] = None) -> List[dict]:
        dirpath = flag("rpcz_dir")
        if not dirpath or n <= 0:
            return []
        # flush pending lines under the lock so history is current, but
        # SCAN outside it — parsing up to 2x rpcz_db_max_bytes of JSON
        # under the write lock would stall every RPC's finish_span. A
        # concurrent rotation mid-scan costs at most a transient miss on
        # this diagnostic page (os.replace is atomic; open fds survive).
        with self._lock:
            if self._buf:
                try:
                    self._flush_locked(dirpath)
                except OSError:
                    self._buf.clear()
        # bounded ring while scanning — never materialize all lines
        rows: Deque[dict] = deque(maxlen=n)
        for old in (True, False):       # aged file first: oldest→newest
            try:
                with open(os.path.join(dirpath,
                                       self.FILE + (".1" if old else "")),
                          encoding="utf-8") as f:
                    for line in f:
                        try:
                            d = json.loads(line)
                        except ValueError:
                            continue
                        if trace_id is None or \
                                int(d.get("trace_id", "0"),
                                    16) == trace_id:
                            rows.append(d)
            except OSError:
                continue
        return list(rows)


    def flush(self) -> None:
        """Force buffered lines to disk (server stop / process exit —
        the last spans before a shutdown are usually the interesting
        ones)."""
        dirpath = flag("rpcz_dir")
        if not dirpath:
            return
        with self._lock:
            if self._buf:
                try:
                    self._flush_locked(dirpath)
                except OSError:
                    self._buf.clear()


global_store = SpanStore()
global_collector = SpanCollector()

import atexit  # noqa: E402  (registration belongs with the store)

atexit.register(global_store.flush)


def new_trace_id() -> int:
    return fast_rand() or 1


def start_server_span(cntl, service: str, method: str) -> Span:
    """CreateServerSpan (span.cpp:149): trace context from the request
    meta, or a fresh trace."""
    trace_id = cntl.trace_id or new_trace_id()
    span = Span(
        trace_id=trace_id,
        span_id=new_trace_id(),
        parent_span_id=cntl.span_id,
        side="server",
        service=service,
        method=method,
        remote_side=str(cntl.remote_side) if cntl.remote_side else "",
        start_us=time.monotonic_ns() // 1000,
        log_id=cntl.log_id,
    )
    cntl.trace_id = trace_id       # propagate to downstream client calls
    cntl.span_id = span.span_id
    return span


def start_client_span(cntl, service: str, method: str) -> Span:
    trace_id = cntl.trace_id or new_trace_id()
    span = Span(
        trace_id=trace_id,
        span_id=new_trace_id(),
        parent_span_id=cntl.span_id,
        side="client",
        service=service,
        method=method,
        start_us=time.monotonic_ns() // 1000,
        log_id=cntl.log_id,
    )
    cntl.trace_id = trace_id
    cntl.span_id = span.span_id
    return span


def finish_span(span: Span, cntl) -> None:
    span.end_us = time.monotonic_ns() // 1000
    span.error_code = cntl.error_code
    if cntl.remote_side and not span.remote_side:
        span.remote_side = str(cntl.remote_side)
    global_collector.submit(span)
    if flag("rpcz_enabled"):
        global_store.write(span)
