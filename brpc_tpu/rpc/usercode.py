"""Usercode backup pool (details/usercode_backup_pool.* +
usercode_in_pthread in the reference): run blocking user handlers on a
reserve pthread pool so fiber workers stay free to pump IO.

Enable with ``ServerOptions(usercode_in_pthread=True)`` — sync handlers
then run on the pool while the dispatch fiber awaits completion; async
handlers keep running on fibers (they are cooperative already)."""

from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

from brpc_tpu.butil.flags import define_flag, flag
from brpc_tpu.fiber.sync import FiberEvent

define_flag("usercode_backup_threads", 16,
            "reserve pthreads for usercode_in_pthread handlers")

_pool: Optional[ThreadPoolExecutor] = None
_pool_lock = threading.Lock()


def _get_pool() -> ThreadPoolExecutor:
    global _pool
    with _pool_lock:
        if _pool is None:
            _pool = ThreadPoolExecutor(
                max_workers=flag("usercode_backup_threads"),
                thread_name_prefix="usercode")
        return _pool


def _postfork_reset() -> None:
    """Fork hygiene: the executor's pthreads exist only in the parent
    — submitting to the inherited pool would queue work nobody runs."""
    global _pool, _pool_lock
    _pool = None
    _pool_lock = threading.Lock()


from brpc_tpu.butil import postfork  # noqa: E402  (registration ships
#                                      with the singleton it resets)

postfork.register("rpc.usercode", _postfork_reset)


async def run_usercode(fn, *args):
    """Run ``fn(*args)`` on the backup pool; the calling fiber suspends
    (not its worker thread) until done."""
    done = FiberEvent()
    box: list = [None, None]

    def run():
        try:
            box[0] = fn(*args)
        except BaseException as e:
            box[1] = e
        done.set()

    _get_pool().submit(run)
    await done.wait()
    if box[1] is not None:
        raise box[1]
    return box[0]
