"""CircuitBreaker: per-server EMA error-rate isolation
(brpc/circuit_breaker.h:25-52) plus the cluster-wide revival gate
(cluster_recover_policy.*): when too much of the cluster is isolated,
stop isolating (otherwise a full outage can never recover).
"""

from __future__ import annotations

import threading
import time
import weakref
from typing import Dict, List

from brpc_tpu.butil.endpoint import EndPoint


class CircuitBreaker:
    """One per server endpoint. Two EMA windows like the reference: a
    short twitchy one and a long stable one; tripping either isolates."""

    SHORT_ALPHA = 0.3
    LONG_ALPHA = 0.02
    ERROR_THRESHOLD = 0.5      # short-window trip
    LONG_THRESHOLD = 0.2       # long-window trip
    MIN_SAMPLES = 5
    BASE_ISOLATION_S = 0.1
    MAX_ISOLATION_S = 30.0

    def __init__(self):
        self._short = 0.0
        self._long = 0.0
        self._samples = 0
        self._isolated_until = 0.0
        self._isolation_s = self.BASE_ISOLATION_S
        self._trips = 0              # lifetime isolation count
        self._lock = threading.Lock()

    def on_call(self, failed: bool) -> None:
        x = 1.0 if failed else 0.0
        with self._lock:
            self._samples += 1
            self._short = (1 - self.SHORT_ALPHA) * self._short + self.SHORT_ALPHA * x
            self._long = (1 - self.LONG_ALPHA) * self._long + self.LONG_ALPHA * x
            if self._samples >= self.MIN_SAMPLES and (
                    self._short > self.ERROR_THRESHOLD
                    or self._long > self.LONG_THRESHOLD):
                # isolate with exponential backoff on repeat trips
                now = time.monotonic()
                if now >= self._isolated_until:
                    self._isolated_until = now + self._isolation_s
                    self._isolation_s = min(self._isolation_s * 2,
                                            self.MAX_ISOLATION_S)
                    self._trips += 1
                self._short = 0.0
                self._samples = 0

    def on_success_streak(self) -> None:
        """Reward sustained health: shrink the next isolation."""
        with self._lock:
            self._isolation_s = max(self.BASE_ISOLATION_S,
                                    self._isolation_s / 2)

    def isolated(self) -> bool:
        return time.monotonic() < self._isolated_until

    def error_rate(self) -> float:
        return self._short

    @property
    def isolated_until(self) -> float:
        """Monotonic instant isolation ends (0.0 = never isolated)."""
        return self._isolated_until

    @property
    def isolation_s(self) -> float:
        """The NEXT trip's isolation duration (the backoff level)."""
        return self._isolation_s

    def snapshot(self) -> dict:
        """Consistent observability snapshot (builtin status page /
        chaos driver): one lock acquisition, plain JSON-able scalars."""
        with self._lock:
            now = time.monotonic()
            return {
                "isolated": now < self._isolated_until,
                "isolated_for_s": max(0.0, self._isolated_until - now),
                "isolation_s": self._isolation_s,
                "error_rate_short": self._short,
                "error_rate_long": self._long,
                "samples": self._samples,
                "trips": self._trips,
            }


# every live ClusterBreakers in the process, for the builtin status
# page: breakers belong to CLIENT cluster channels, but operators debug
# them from whatever server the process also runs — the page shows
# process-wide state (weakly held: a closed channel's breakers vanish
# with it)
_registry: "weakref.WeakSet[ClusterBreakers]" = weakref.WeakSet()


def all_breaker_snapshots() -> Dict[str, dict]:
    """Per-endpoint breaker snapshots across every cluster channel in
    the process (endpoints reached by several channels report the LAST
    channel's view — they are distinct breakers by design)."""
    out: Dict[str, dict] = {}
    for cb in list(_registry):
        out.update(cb.snapshot())
    return out


class ClusterBreakers:
    """Breaker per endpoint + the recovery gate
    (ClusterRecoverPolicy: if >= half the cluster is isolated, ignore
    isolation so revival traffic can flow)."""

    RECOVER_FRACTION = 0.5

    def __init__(self):
        self._breakers: Dict[EndPoint, CircuitBreaker] = {}
        self._lock = threading.Lock()
        _registry.add(self)

    def snapshot(self) -> Dict[str, dict]:
        with self._lock:
            items = list(self._breakers.items())
        return {str(ep): b.snapshot() for ep, b in items}

    def breaker(self, ep: EndPoint) -> CircuitBreaker:
        b = self._breakers.get(ep)
        if b is None:
            with self._lock:
                b = self._breakers.setdefault(ep, CircuitBreaker())
        return b

    def on_call(self, ep: EndPoint, failed: bool) -> None:
        self.breaker(ep).on_call(failed)

    def isolated_set(self, servers) -> set:
        """Endpoints to exclude, honoring the cluster recover gate."""
        iso = {s for s in servers
               if s in self._breakers and self._breakers[s].isolated()}
        if servers and len(iso) >= max(1, int(len(servers) * self.RECOVER_FRACTION)):
            return set()  # too many down: let traffic probe everything
        return iso
