"""Server: service registry + acceptor + graceful stop
(brpc/server.{h,cpp}: StartInternal :750, Stop/Join :691).

start() listens on any registered transport scheme; accepted conns become
Sockets whose input callback is the shared InputMessenger. The server
rides along in socket.user_data so protocol dispatch finds it
(the reference reaches the server through the Socket's acceptor back-ref).
"""

from __future__ import annotations

import threading
import weakref
from typing import Dict, List, Optional

from brpc_tpu.butil.endpoint import EndPoint, str2endpoint
from brpc_tpu.butil.flags import define_flag, flag
from brpc_tpu.bvar.latency_recorder import LatencyRecorder
from brpc_tpu.fiber import TaskControl, global_control
from brpc_tpu.rpc.service import Method, Service
from brpc_tpu.transport import syscall_stats as _syscall_stats
from brpc_tpu.transport.base import get_transport
from brpc_tpu.transport.input_messenger import InputMessenger
from brpc_tpu.transport.socket import Socket

define_flag("server_queue_shed_ms", 200.0,
            "queue-delay shed budget: a request whose arrival-to-"
            "dispatch time exceeds this is rejected with ELIMIT before "
            "the handler runs (default gate for adaptive-limiter "
            "servers; ServerOptions.queue_delay_shed_ms overrides "
            "per server)", validator=lambda v: v > 0)

_nlimit_shed = None   # lazily bound server_dispatch.nlimit_shed (the
#                       Adder lives with the other dispatch counters;
#                       importing it at module top would be a cycle for
#                       nothing — the reject path is cold)


def _count_limit_shed() -> None:
    global _nlimit_shed
    v = _nlimit_shed
    if v is None:
        from brpc_tpu.rpc.server_dispatch import nlimit_shed
        v = _nlimit_shed = nlimit_shed
    v.add(1)


# the last-started server, weakly held: the process-wide
# server_concurrency_limit/_inflight gauges read through it (multiple
# servers in one process: the newest wins, like the other server vars)
_limiter_var_server = None


def _expose_limiter_vars(server) -> None:
    global _limiter_var_server
    _limiter_var_server = weakref.ref(server)
    from brpc_tpu.bvar.reducer import PassiveStatus

    def _read(attr_fn, default=0):
        ref = _limiter_var_server
        s = ref() if ref is not None else None
        if s is None:
            return default
        return attr_fn(s)

    PassiveStatus(lambda: _read(lambda s: s.concurrency_limit() or 0)) \
        .expose("server_concurrency_limit")
    PassiveStatus(lambda: _read(lambda s: s.concurrency)) \
        .expose("server_concurrency_inflight")
    # the DAGOR admission threshold: 0 while calm; merged shard views
    # take the max (the shard_group "threshold" scalar rule)
    PassiveStatus(lambda: _read(
        lambda s: s._admission.wire_threshold()
        if s._admission is not None else 0)) \
        .expose("server_admission_threshold")


# process-wide graceful-SIGTERM state: weak so stopped/forgotten servers
# don't linger, installed once so restart cycles don't chain handlers
_sigterm_registry: "weakref.WeakSet" = weakref.WeakSet()
_sigterm_lock = threading.Lock()
_sigterm_installed = False


def _install_sigterm_handler_once() -> None:
    global _sigterm_installed
    with _sigterm_lock:
        if _sigterm_installed:
            return
        import signal
        prev = signal.getsignal(signal.SIGTERM)

        def _on_term(signum, frame):
            for srv in list(_sigterm_registry):
                try:
                    srv.stop()
                except Exception:
                    pass
            if callable(prev):
                prev(signum, frame)
            elif prev == signal.SIG_DFL:
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
                signal.raise_signal(signal.SIGTERM)

        try:
            signal.signal(signal.SIGTERM, _on_term)
            _sigterm_installed = True
        except ValueError:
            pass  # not the main thread: best-effort


class ServerOptions:
    def __init__(self, num_workers: Optional[int] = None,
                 max_concurrency=None,
                 method_max_concurrency: Optional[Dict[str, object]] = None,
                 queue_delay_shed_ms: Optional[float] = None,
                 request_costs=None,
                 priority_admission: Optional[bool] = None,
                 auth_token: Optional[str] = None,
                 auth=None, interceptor=None,
                 enable_builtin_services: bool = True,
                 redis_service=None, thrift_service=None,
                 nshead_service=None, esp_service=None,
                 mongo_service_adaptor=None, rtmp_service=None,
                 session_local_data_factory=None,
                 session_local_data_reset=None,
                 usercode_in_pthread: bool = False,
                 health_reporter=None):
        self.num_workers = num_workers
        # server-wide in-flight cap: an int, or an adaptive spec string
        # ('auto[:initial[:min[:max]]]' | 'constant:N' | 'timeout:MS' —
        # the reference's -max_concurrency vocabulary); backed by a
        # ConcurrencyLimiter driven from both dispatch paths
        self.max_concurrency = max_concurrency
        # per-method caps: {"Service.Method": spec} — consulted after
        # the server-wide limiter (rpc/concurrency_limiter.py)
        self.method_max_concurrency = method_max_concurrency
        # queue-delay shed gate (DAGOR-style overload control): requests
        # whose arrival-to-dispatch time exceeds this budget are shed
        # with ELIMIT before the handler runs. None = default ON (from
        # the server_queue_shed_ms flag) when max_concurrency is an
        # adaptive spec, OFF otherwise; a number forces it on.
        self.queue_delay_shed_ms = queue_delay_shed_ms
        # cost-weighted limiter slots (rpc/admission.CostModel): True
        # charges each request a weight from its size + its method's
        # expected-latency bucket, so a 4MB streaming call draws more
        # of the concurrency limit than a 4B echo. None/False = every
        # request costs exactly one slot (the PR 10 behavior).
        self.request_costs = request_costs
        # DAGOR two-level priority admission (rpc/admission.py): when
        # the limiter or queue-delay gate reports overload, requests
        # below the adaptive (business, user) threshold are shed with
        # EPRIORITYSHED before parse/handler, and the threshold rides
        # every response back to senders. None = default ON whenever
        # any overload organ is configured (a limiter or the queue
        # gate) — inert until overload AND inert on uniform-priority
        # traffic (the top-class clamp); False forces it off.
        self.priority_admission = priority_admission
        self.auth_token = auth_token
        # pluggable Authenticator (rpc/auth.py; brpc/authenticator.h) —
        # wins over auth_token, which is sugar for TokenAuthenticator
        self.auth = auth
        # Interceptor (brpc/interceptor.h): callable(cntl) -> None accepts,
        # (error_code, reason) or raise InterceptorError rejects
        self.interceptor = interceptor
        self.enable_builtin_services = enable_builtin_services
        # server-side redis command table (ServerOptions::redis_service in
        # the reference, brpc/redis.h:240)
        self.redis_service = redis_service
        # native thrift method table (brpc/thrift_service.h)
        self.thrift_service = thrift_service
        # legacy family adaptors (nshead_service.h, esp_message.h,
        # mongo_service_adaptor.h)
        self.nshead_service = nshead_service
        self.esp_service = esp_service
        self.mongo_service_adaptor = mongo_service_adaptor
        # live publish/play relay registry (rtmp.h RtmpService)
        self.rtmp_service = rtmp_service
        # per-request reusable objects (ServerOptions.
        # session_local_data_factory, simple_data_pool.h)
        self.session_local_data_factory = session_local_data_factory
        self.session_local_data_reset = session_local_data_reset
        # run blocking sync handlers on a reserve pthread pool
        # (usercode_in_pthread + usercode_backup_pool in the reference)
        self.usercode_in_pthread = usercode_in_pthread
        # custom /health responder (brpc/health_reporter.h): callable
        # (server) -> bytes|str|(status:int, content_type:str, body) —
        # lets apps gate readiness on their own state
        self.health_reporter = health_reporter


class Server:
    def __init__(self, options: Optional[ServerOptions] = None,
                 control: Optional[TaskControl] = None):
        self.options = options or ServerOptions()
        self._control = control or global_control()
        self._messenger = InputMessenger(control=self._control)
        if self.options.session_local_data_factory is not None:
            from brpc_tpu.rpc.data_pool import SimpleDataPool
            self.session_local_pool = SimpleDataPool(
                self.options.session_local_data_factory,
                reset=self.options.session_local_data_reset)
        else:
            self.session_local_pool = None
        self._services: Dict[str, Service] = {}
        self._build_limiters()
        self._listener = None
        self._endpoint: Optional[EndPoint] = None
        self._conns: List[Socket] = []
        self._conns_lock = threading.Lock()
        self._running = False
        self._stopped_event = threading.Event()
        self.method_status: Dict[str, LatencyRecorder] = {}
        self._native_echo = None        # (svc_bytes, mth_bytes, key)
        self._fast_drain_hook = None    # lazy; False = unavailable
        self.concurrency = 0            # in-flight requests
        self._concurrency_lock = threading.Lock()
        self.nprocessed = 0
        self.nerror = 0
        self._shard_group = None        # supervisor handle (num_shards>1)
        self.shard_index = None         # set in shard workers
        self._serving = None            # GenerateService handle (serving/)

    def _build_limiters(self) -> None:
        """Resolve the concurrency-limiter specs (construction and
        postfork re-arm share this: a forked shard must not inherit the
        parent limiter's inflight count or lock)."""
        from brpc_tpu.rpc.concurrency_limiter import new_limiter
        o = self.options
        self._limiter = new_limiter(o.max_concurrency)
        self._method_limiters = {
            k: new_limiter(v)
            for k, v in (o.method_max_concurrency or {}).items()}
        qd = o.queue_delay_shed_ms
        if qd is None and isinstance(o.max_concurrency, str):
            # adaptive servers get the queue-delay gate by default: a
            # saturated node must reject in microseconds, not let queued
            # work time out in seconds (The Tail at Scale / DAGOR)
            qd = flag("server_queue_shed_ms")
        self._queue_shed_ns = int(qd * 1e6) if qd else 0
        # DAGOR priority admission + weighted request costs (ISSUE 14).
        # Rebuilt here so a forked shard gets fresh window/threshold
        # state, like the limiters above. Admission defaults ON where
        # an overload organ exists to signal it (any limiter, or the
        # queue gate) — it stays inert until overload AND never sheds
        # uniform-priority traffic (the top-class clamp), so servers
        # without priority-tagged callers keep exact PR 10 behavior.
        from brpc_tpu.rpc.admission import (AdmissionController,
                                            CostModel, admission_enabled)
        want_adm = o.priority_admission
        if want_adm is None:
            want_adm = (self._limiter is not None
                        or bool(self._method_limiters)
                        or self._queue_shed_ns > 0)
        self._admission = AdmissionController() \
            if (want_adm and admission_enabled()) else None
        self._cost_model = CostModel(self) if o.request_costs else None

    def concurrency_limit(self) -> Optional[int]:
        """The server-wide limiter's current limit (None = unlimited) —
        the /status saturation pane's ``concurrency_limit``."""
        lim = self._limiter
        return lim.max_concurrency if lim is not None else None

    # ------------------------------------------------------------ services
    def add_service(self, service: Service) -> None:
        if self._running:
            raise RuntimeError("add_service after start")
        if service.name in self._services:
            raise ValueError(f"service {service.name!r} already added")
        self._services[service.name] = service
        for m in service.methods.values():
            # precomputed /status key: an f-string per request adds up
            m.full_name = f"{service.name}.{m.name}"
            if m.native_kind == "echo" and self._native_echo is None:
                # ONE native echo target per server (the C serving loop
                # matches a single (service, method) pair); additional
                # echo-marked methods serve through the normal paths
                self._native_echo = (service.name.encode(),
                                     m.name.encode(), m.full_name)

    def find_method(self, service_name: str, method_name: str) -> Optional[Method]:
        svc = self._services.get(service_name)
        if svc is None:
            return None
        return svc.methods.get(method_name)

    def services(self) -> Dict[str, Service]:
        return dict(self._services)

    # ----------------------------------------------------------- lifecycle
    def start(self, address: str | EndPoint,
              num_shards: Optional[int] = None,
              shard_options=None) -> EndPoint:
        """Listen and serve; returns the bound endpoint (with the real
        port for tcp://host:0).

        ``num_shards=N`` (N>1, tcp only) turns this call into
        shard-group serving: N worker processes each bind the same
        port with SO_REUSEPORT and run a fully private stack — the
        GIL-parallel escape hatch mapping the reference's -reuse_port
        (see rpc/shard_group.py). This process becomes the SUPERVISOR:
        it serves no traffic itself; stop()/join() drain the group."""
        if self._running:
            raise RuntimeError("server already started")
        if num_shards is not None and num_shards > 1:
            import copy
            from brpc_tpu.rpc.shard_group import (ShardGroup,
                                                  ShardGroupOptions)
            # copy before overriding num_shards: the caller may reuse
            # their options object for another group
            opts = copy.copy(shard_options) if shard_options is not None \
                else ShardGroupOptions()
            opts.num_shards = num_shards
            self._shard_group = ShardGroup(self, address, opts)
            self._endpoint = self._shard_group.start()
            self._running = True
            self._stopped_event.clear()
            return self._endpoint
        ep = address if isinstance(address, EndPoint) else str2endpoint(address)
        if self.options.enable_builtin_services:
            from brpc_tpu.builtin.services import add_builtin_services
            from brpc_tpu.bvar.default_variables import (
                expose_default_variables)
            add_builtin_services(self)
            expose_default_variables()   # process_* vars (idempotent)
            # socket traffic + fast-lane counters follow the same
            # lifecycle: their import-time expose is stripped forever
            # by an unexpose_all() (test fixtures) — re-register here
            # like the process_* vars, so /vars keeps them for any
            # server started afterward in the process
            from brpc_tpu.rpc.server_dispatch import (nlimit_shed,
                                                      npriority_shed,
                                                      nshed)
            from brpc_tpu.transport.socket import (_wqueue_peak_window,
                                                   npluck_defer,
                                                   npluck_fast, nreads,
                                                   nwqueue_bytes, nwrites)
            for var, name in ((nwrites, "socket_writes"),
                              (nreads, "socket_read_bytes"),
                              (npluck_fast, "pluck_fast_responses"),
                              (npluck_defer, "pluck_defers"),
                              (nwqueue_bytes, "socket_wqueue_bytes"),
                              # the shed counters are anomaly-watchdog
                              # keys: their trend rings (and the
                              # /status saturation links) must survive
                              # an unexpose_all like every counter here
                              (nshed, "server_deadline_shed"),
                              (nlimit_shed, "server_limit_shed"),
                              (npriority_shed, "server_priority_shed")):
                var.expose(name)
            from brpc_tpu.bvar.reducer import PassiveStatus
            wq_peak = _wqueue_peak_window()
            PassiveStatus(lambda: wq_peak.get_value() or 0).expose(
                "socket_wqueue_peak_10s")
            # connection-cost census + stall-watchdog bvars follow the
            # same re-expose lifecycle as the socket counters above
            from brpc_tpu.transport.event_dispatcher import (
                expose_stall_vars)
            from brpc_tpu.transport.socket import expose_conn_census_vars
            expose_conn_census_vars()
            expose_stall_vars()
            # syscall-accounting floor (syscalls_recv/writev/accept +
            # the syscalls_per_rpc derived key) — same survival rule
            from brpc_tpu.transport.syscall_stats import (
                expose_syscall_vars)
            expose_syscall_vars()
            # per-backend client stat cells (labeled prometheus family)
            # follow the same re-expose lifecycle
            from brpc_tpu.rpc.backend_stats import expose_backend_vars
            expose_backend_vars()
            # device-lane stat cells + the ici_* counters (lane status,
            # unpulled/leaked/reclaimed) — the unexpose_all survival
            # rule again: a restart must not drop them from /vars
            from brpc_tpu.transport.device_stats import expose_device_vars
            expose_device_vars()
            import sys as _sys
            _ici_mod = _sys.modules.get("brpc_tpu.transport.ici")
            if _ici_mod is not None:
                _ici_mod.expose_ici_vars()
            # overload-control gauges (limiter limit + inflight) for
            # prometheus and the merged shard views
            _expose_limiter_vars(self)
            # server-wide trend triple for /timeline + cluster_top's
            # spark columns: processed/errors as DECLARED delta series
            # (a monotone passive graphs as qps only when its ring
            # knows it is a counter), worst instant method p99 as a
            # max series — all following the unexpose_all re-expose
            # lifecycle like every counter above. Weakly bound like
            # _expose_limiter_vars: the registry outlives any one
            # Server, and a strong closure would pin a stopped server
            # (and its reservoirs) for the process lifetime
            from brpc_tpu.bvar.series import declare_series_kind
            wref = weakref.ref(self)

            def _trend(attr_fn, default=0):
                s = wref()
                return attr_fn(s) if s is not None else default

            def _worst_p99(srv):
                best = 0.0
                for lr in list(srv.method_status.values()):
                    try:
                        best = max(best, lr.latency_percentile(0.99))
                    except Exception:
                        pass
                return round(best, 1)
            PassiveStatus(lambda: _trend(lambda s: s.nprocessed)).expose(
                "server_processed")
            PassiveStatus(lambda: _trend(lambda s: s.nerror)).expose(
                "server_errors")
            PassiveStatus(lambda: _trend(_worst_p99, 0.0)).expose(
                "server_latency_p99_us")
            declare_series_kind("server_processed", "delta")
            declare_series_kind("server_errors", "delta")
            declare_series_kind("server_latency_p99_us", "max")
            # scheduler saturation trio (runqueue depth/peak, worker
            # busy fraction) + fiber counters: /vars + prometheus
            self._control.expose_vars()
            # best-effort: SIGUSR2 -> fiber stacks on stderr, so
            # tools/fiber_stacks.py <pid> works like the reference's
            # gdb_bthread_stack.py (no-op off the main thread)
            from brpc_tpu.fiber.stacks import enable_stack_dump_signal
            enable_stack_dump_signal()
        # serving lane: build THIS process's model replica + batcher and
        # register the engine with the fiber workers before traffic can
        # land. A shard worker reaches here post-fork with the module
        # registry freshly cleared, so each shard runs a private
        # replica — the supervisor (shard-group path above) runs none.
        if self._serving is not None:
            self._serving.on_server_start(self)
        transport = get_transport(ep.scheme)
        self._listener = transport.listen(ep, self._on_new_conn)
        self._endpoint = self._listener.endpoint
        self._running = True
        self._stopped_event.clear()
        self._maybe_install_sigterm()
        # flight recorder: continuous profiler + event-loop stall
        # watchdog ride a serving process (honors the hz flag at
        # runtime; a forked shard re-starts its own — the postfork
        # registry dropped the parent's recorder)
        from brpc_tpu.builtin.flight_recorder import global_recorder
        global_recorder().ensure_running()
        # incident time machine: re-expose the incident bvars (the PR 2
        # unexpose_all survival rule), hand the manager this server for
        # its bundler snapshots, and prime the artifact ledger so
        # artifacts surviving a restart show up immediately
        from brpc_tpu.incident.manager import (attach_incident_server,
                                               expose_incident_vars)
        expose_incident_vars()
        attach_incident_server(self)
        # trend rings + anomaly watchdog: make sure the bvar sampler's
        # tick thread runs even with no windowed reducers yet, and bind
        # the watchdog's annotation imports on THIS thread before the
        # sampler can need them (the PR 8 sampler-import rule)
        from brpc_tpu.bvar.series import ensure_series
        ensure_series()
        return self._endpoint

    def _maybe_install_sigterm(self) -> None:
        """graceful_quit_on_sigterm (server.cpp graceful Stop/Join:691):
        SIGTERM drains running servers instead of killing the process
        mid-request. One process-wide handler over a weak registry —
        start/stop cycles must not chain handlers or pin dead Servers."""
        from brpc_tpu.butil.flags import flag
        if not flag("graceful_quit_on_sigterm"):
            return
        _sigterm_registry.add(self)
        _install_sigterm_handler_once()

    @property
    def endpoint(self) -> Optional[EndPoint]:
        return self._endpoint

    def _on_new_conn(self, conn) -> None:
        sock = Socket(conn, on_input=self._messenger.on_new_messages,
                      control=self._control)
        sock.user_data["server"] = self
        if self._native_echo is not None:
            # native per-event serving (fastcore serve_drain); the hook
            # re-checks runtime gates (flags, cut-through state) per
            # pass and self-disables on non-fd transports
            fdr = self._fast_drain_hook
            if fdr is None:    # resolve once; False = unavailable
                from brpc_tpu.rpc.server_dispatch import make_fast_drain
                fdr = self._fast_drain_hook = make_fast_drain(self) or False
            if fdr is not False and not sock._ring_attached:
                # ring lane: the dispatcher tick is this fd's only recv
                # authority — the fd-draining serve_drain hook would
                # read bytes that arrived AFTER chunks the ring already
                # queued, serving them out of order. The portal-based
                # native echo (input_messenger's nserve) still engages
                # on ring-delivered bytes.
                sock.fast_drain = fdr
        with self._conns_lock:
            self._conns.append(sock)
            # opportunistic sweep of dead conns
            if len(self._conns) > 64:
                self._conns = [s for s in self._conns if not s.failed]

    def connections(self) -> List[Socket]:
        with self._conns_lock:
            return [s for s in self._conns if not s.failed]

    def stop(self) -> None:
        """Stop accepting; existing connections are closed after in-flight
        requests drain (graceful, server.cpp:691)."""
        if not self._running:
            return
        self._running = False
        _sigterm_registry.discard(self)
        if self._shard_group is not None:
            self._shard_group.stop()
            self._stopped_event.set()
            return
        if self._listener is not None:
            self._listener.stop()
        if self._serving is not None:
            # unregister the engine from the worker loops and retire
            # in-flight sequences (their clients are being drained)
            self._serving.on_server_stop(self)

    def join(self, timeout_s: float = 5.0) -> None:
        """Wait for in-flight requests, then close connections."""
        if self._shard_group is not None:
            self._shard_group.join(timeout_s)
            return
        import time
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._concurrency_lock:
                if self.concurrency == 0:
                    break
            time.sleep(0.005)
        with self._conns_lock:
            conns, self._conns = self._conns, []
        for s in conns:
            s.set_failed(ConnectionError("server stopped"))
        self._stopped_event.set()

    def run_until_asked_to_quit(self) -> None:
        """Block until SIGINT/SIGTERM, then drain and stop.

        Long-running example/tool servers get two safeguards for free
        (this harness shares ONE device tunnel — an orphaned jax-capable
        process wedges it for every later client, which cost the bench
        its device capture twice): a parent-death watchdog (orphaned →
        exit) and a pidfile under .pids/ so the bench preflight can
        reap leftovers. Opt out with BRPC_TPU_NO_PARENT_WATCHDOG=1
        (daemons intentionally outliving their launcher)."""
        import os
        import signal
        ev = threading.Event()
        for sig in (signal.SIGINT, signal.SIGTERM):
            signal.signal(sig, lambda *_: ev.set())
        pidfile = None
        watchdog = not os.environ.get("BRPC_TPU_NO_PARENT_WATCHDOG")
        from brpc_tpu.butil.pidfile import remove_pidfile, write_pidfile
        pidfile = write_pidfile(f"server-{self._endpoint}")
        parent = os.getppid()
        try:
            while not ev.is_set():
                ev.wait(1.0)
                if watchdog and os.getppid() != parent:
                    break     # orphaned: parent died without SIGTERM
        finally:
            remove_pidfile(pidfile)
        self.stop()
        self.join()

    def _postfork_child_reset(self) -> None:
        """Re-arm this Server for a forked shard worker: the template's
        services/options survive the fork as plain data, but every
        runtime organ — TaskControl, InputMessenger, listener, conns,
        per-method recorders — referenced the PARENT's (now reset)
        machinery and must be rebuilt against the child's fresh
        singletons before start() runs here."""
        self._control = global_control()
        self._messenger = InputMessenger(control=self._control)
        self._listener = None
        self._endpoint = None
        self._conns = []
        self._conns_lock = threading.Lock()
        self._concurrency_lock = threading.Lock()
        self._running = False
        self._stopped_event = threading.Event()
        self._fast_drain_hook = None
        self.method_status = {}
        self.concurrency = 0
        self.nprocessed = 0
        self.nerror = 0
        self._build_limiters()   # fresh inflight counts + locks
        self._shard_group = None
        if self.session_local_pool is not None:
            from brpc_tpu.rpc.data_pool import SimpleDataPool
            self.session_local_pool = SimpleDataPool(
                self.options.session_local_data_factory,
                reset=self.options.session_local_data_reset)

    # ----------------------------------------------------------- accounting
    def on_request_start(self, method_key: Optional[str] = None,
                         nbytes: int = 0, level: int = 0,
                         level_counted: bool = False) -> float:
        """Admission gate, both dispatch paths (classic AND the turbo
        lane) plus every protocol front-end: consult the server-wide
        limiter, then the method's (when configured). Returns the
        request's admitted COST (>= 1.0, truthy — weighted slots when
        ``ServerOptions(request_costs=True)``, else exactly 1.0) or
        0.0 (falsy) when the caller must reject with ELIMIT; the SAME
        cost must ride to on_request_end so the weighted release
        balances. ``level`` is the request's admission level — limiter
        rejects feed it to the priority-admission controller as
        overload evidence (``level_counted`` = the engaged dispatch
        path already tallied it through admit_level). Limiter locks
        are leaves — never taken under _concurrency_lock."""
        cm = self._cost_model
        cost = cm.request_cost(method_key, nbytes) if cm is not None \
            else 1.0
        lim = self._limiter
        if lim is not None and not lim.on_requested(cost):
            _count_limit_shed()
            adm = self._admission
            if adm is not None:
                adm.signal_overload(level, level_counted)
            return 0.0
        if self._method_limiters and method_key is not None:
            ml = self._method_limiters.get(method_key)
            if ml is not None and not ml.on_requested(cost):
                if lim is not None:
                    # release the server-wide slot the gate above took
                    lim.on_responded(0.0, True, cost)
                _count_limit_shed()
                adm = self._admission
                if adm is not None:
                    adm.signal_overload(level, level_counted)
                return 0.0
        with self._concurrency_lock:
            self.concurrency += 1
        return cost

    def account_native_batch(self, method_key: str, n: int,
                             total_us: float) -> None:
        """Stats for a batch the C serving loop handled (serve_scan):
        native methods never block, so they bypass the concurrency
        gate; processed counts and /status latency still land."""
        _syscall_stats.note_rpc_messages(n)
        with self._concurrency_lock:
            self.nprocessed += n
        lr = self.method_status.get(method_key)
        if lr is None:
            lr = self.method_status.setdefault(method_key, LatencyRecorder())
        lr.record_batch(total_us / n, n)

    def on_request_end(self, method_key: str, latency_us: float,
                       failed: bool, cost: float = 1.0):
        with self._concurrency_lock:
            self.concurrency -= 1
            self.nprocessed += 1
            if failed:
                self.nerror += 1
        lim = self._limiter
        if lim is not None:
            lim.on_responded(latency_us, failed, cost)
        if self._method_limiters:
            ml = self._method_limiters.get(method_key)
            if ml is not None:
                ml.on_responded(latency_us, failed, cost)
        lr = self.method_status.get(method_key)
        if lr is None:
            lr = self.method_status.setdefault(method_key, LatencyRecorder())
        lr.record(latency_us)

    @property
    def is_running(self) -> bool:
        return self._running
