"""Streaming RPC (brpc/stream.h:103-120, stream_impl.h, SURVEY.md §2.6).

Stream setup piggybacks on a normal RPC (stream ids ride RpcMeta's
stream_settings on the request and response), after which STREAM frames —
meta with stream_settings but neither request nor response — flow both
ways on the same socket with credit-based flow control:

  - each side starts with ``initial_credits`` frames of send budget
  - the receiver returns credits in batches (piggybacked on its own
    frames or as bare credit grants) after delivering frames
  - a writer with no credits parks on a butex until a grant arrives

Device arrays stream over the same device lane as unary RPC. Ordered
delivery comes from the socket's FIFO write queue + per-stream
ExecutionQueue on the receive side (the reference's per-stream
ExecutionQueue write path, SURVEY.md §2.6).
"""

from __future__ import annotations

import threading
from typing import Callable, List, Optional

from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.butil.resource_pool import ResourcePool
from brpc_tpu.fiber import ExecutionQueue, global_control
from brpc_tpu.fiber.butex import Butex, WAIT_TIMEOUT
from brpc_tpu.protocol.proto import tpu_rpc_meta_pb2 as pb
from brpc_tpu.protocol.tpu_std import (_HDR, MAGIC, _varint, pack_message)

_stream_pool: ResourcePool = ResourcePool()
_stream_pool.insert(None)  # stream id 0 = invalid (proto3 zero default)

DEFAULT_CREDITS = 64
CREDIT_BATCH = 16  # grant credits back every K delivered frames


def _release_stream_slot(sock) -> None:
    """Undo one bind_socket count (stream closed or rebound away)."""
    n = sock.user_data.get("bound_streams", 0)
    if n > 0:
        sock.user_data["bound_streams"] = n - 1


class StreamOptions:
    def __init__(self, on_received: Optional[Callable] = None,
                 initial_credits: int = DEFAULT_CREDITS):
        self.on_received = on_received
        self.initial_credits = initial_credits


class Stream:
    def __init__(self, options: Optional[StreamOptions] = None):
        self.options = options or StreamOptions()
        self.id: int = _stream_pool.insert(self)
        self.peer_id: int = 0
        self.socket = None
        self.closed = False
        self.remote_closed = False
        self._frame_seq = 0
        self._credits = Butex(self.options.initial_credits)
        self._pending_grants = 0
        self._grant_lock = threading.Lock()
        self._recv_q = ExecutionQueue(self._deliver, name=f"stream_{self.id}")
        self._close_cbs: List[Callable] = []
        self._close_lock = threading.Lock()
        from brpc_tpu.fiber.sync import FiberEvent
        self._established = FiberEvent()

    def _on_established(self) -> None:
        """Peer id bound (client: response arrived; server: accept).
        Flush any credit grants deferred while peer_id was unknown."""
        self._established.set()
        with self._grant_lock:
            grant = 0
            if self._pending_grants >= CREDIT_BATCH:
                grant, self._pending_grants = self._pending_grants, 0
        if grant and not self.closed:
            self._send_frame(b"", None, credits=grant, data=False)

    # --------------------------------------------------------------- write
    async def write(self, payload: bytes | IOBuf = b"",
                    device_arrays: Optional[List] = None,
                    timeout_s: Optional[float] = 10.0) -> bool:
        """Send one frame; parks on the credit butex when the window is
        exhausted. Returns False if the stream closed."""
        if self.closed or self.remote_closed:
            return False
        if self.peer_id == 0:
            # establishment still in flight: a frame to stream id 0 would
            # be dropped and its credit lost
            if not await self._established.wait(timeout_s):
                return False
            if self.closed or self.remote_closed:
                return False
        while True:
            # closed check BEFORE acquiring: closure bumps the credit
            # word with a sentinel (so parks short-circuit) — acquiring
            # first would "spend" sentinel credits on a dead stream
            if self.closed or self.remote_closed:
                return False
            v = self._credits.value
            if v > 0 and self._credits.compare_exchange(v, v - 1):
                break
            r = await self._credits.wait(expected=0, timeout_s=timeout_s)
            if r == WAIT_TIMEOUT:
                return False
        self._send_frame(payload, device_arrays)
        return True

    def write_nowait(self, payload: bytes | IOBuf = b"",
                     device_arrays: Optional[List] = None) -> bool:
        """Non-blocking write: fails immediately when out of credits or
        before the stream is established."""
        if self.closed or self.remote_closed or self.peer_id == 0:
            return False
        while True:
            v = self._credits.value
            if v <= 0:
                return False
            if self._credits.compare_exchange(v, v - 1):
                break
        self._send_frame(payload, device_arrays)
        return True

    def _send_frame(self, payload, device_arrays, close: bool = False,
                    credits: int = 0, data: bool = True) -> None:
        if not device_arrays and \
                isinstance(payload, (bytes, bytearray, memoryview)):
            if not isinstance(payload, bytes):
                # normalize ONCE: len(memoryview) counts elements, not
                # bytes, for itemsize > 1 — sizing the header off it
                # would desync the wire
                payload = bytes(payload)
            # fast pack: the meta is fully determined by four small
            # fields — hand-encode it (bit-identical to the pb
            # serializer: ascending field numbers, minimal varints;
            # golden-pinned by tests) instead of building an RpcMeta
            # per frame. stream_id=1, frame_seq=3, credits=4, close=5
            # inside stream_settings (RpcMeta field 6); payload bytes
            # ride zero-copy for big frames.
            inner = b"\x08" + _varint(self.peer_id)
            if data:
                self._frame_seq += 1
                inner += b"\x18" + _varint(self._frame_seq)
            if credits:
                inner += b"\x20" + _varint(credits)
            if close:
                inner += b"\x28\x01"
            meta_bytes = b"\x32" + _varint(len(inner)) + inner
            pl = len(payload)
            hdr = _HDR.pack(MAGIC, len(meta_bytes) + pl,
                            len(meta_bytes)) + meta_bytes
            if pl <= 65536:
                # graftlint: disable=callback-under-lock -- callers may
                # hold their own sender lock for token ORDER (the
                # serving _StreamSender does); Socket.write only queues
                # — it never parks, and failure paths flip flags
                self.socket.write(hdr + payload)
            else:
                wire = IOBuf()
                wire.append(hdr)
                wire.append_user_data(payload)
                # graftlint: disable=callback-under-lock -- see the
                # small-frame branch above: write only queues
                self.socket.write(wire)
            return
        meta = pb.RpcMeta()
        ss = meta.stream_settings
        ss.stream_id = self.peer_id
        if data:
            # frame_seq marks DATA frames (they consume a credit and must
            # be delivered, even with an empty payload); bare credit grants
            # and close frames leave it 0
            self._frame_seq += 1
            ss.frame_seq = self._frame_seq
        if close:
            ss.close = True
        if credits:
            ss.credits = credits
        use_lane = bool(device_arrays) and self.socket.conn.supports_device_lane
        wire, lane = pack_message(meta, payload, device_arrays=device_arrays,
                                  device_lane=use_lane)
        if lane is not None:
            self.socket.write_device_payload(lane)
        # graftlint: disable=callback-under-lock -- see _send_frame's
        # raw-frame branch: write only queues, sender locks order tokens
        self.socket.write(wire)

    # -------------------------------------------------------------- receive
    def _on_frame(self, msg) -> None:
        ss = msg.meta.stream_settings
        if ss.credits:
            self._credits.fetch_add(ss.credits)
            self._credits.wake_all()
        if ss.close:
            self._remote_close_once()
            return
        if ss.frame_seq:  # DATA frame (possibly empty payload)
            self._recv_q.execute(("frame", msg))

    async def _deliver(self, batch) -> None:
        import inspect
        for kind, msg in batch:
            if kind == "close":
                for cb in self._close_cbs:
                    try:
                        cb(self)
                    except Exception:
                        pass
                continue
            if self.options.on_received is not None:
                try:
                    r = self.options.on_received(self, msg)
                    if inspect.isawaitable(r):
                        await r  # runs in the drainer fiber: stays serial
                except Exception:
                    import logging
                    logging.getLogger("brpc_tpu.rpc").exception(
                        "stream on_received failed")
            with self._grant_lock:
                self._pending_grants += 1
                grant = 0
                if self._pending_grants >= CREDIT_BATCH and self.peer_id:
                    grant, self._pending_grants = self._pending_grants, 0
            if grant and not self.closed:
                self._send_frame(b"", None, credits=grant, data=False)

    # ------------------------------------------------------ socket binding
    def bind_socket(self, sock) -> None:
        """Attach the ESTABLISHED stream's transport socket and
        subscribe to its failure: a peer dying mid-stream must CLOSE
        the stream (fire on_close, wake blocked writers) — the
        reference fails the stream when its connection breaks
        (stream.cpp on the socket's SetFailed path). Only called once
        the stream is established on this socket (server accept /
        client response) — binding on SEND attempts would let a failed
        first attempt kill a stream whose retried setup then succeeds.
        Idempotent per socket; a previous socket's subscription is
        dropped so a long-lived multiplexed socket doesn't accumulate
        dead streams."""
        # track the SUBSCRIBED socket separately from self.socket: the
        # send path plain-assigns self.socket before establishment, so
        # comparing against it would skip the subscription entirely
        prev = getattr(self, "_subscribed_sock", None)
        # streams write frames independently of the response path: the
        # cut-through serving gate must know this socket can interleave.
        # Counted per bound stream and released on close/unbind, so a
        # connection that once carried a stream isn't degraded forever.
        if prev is not sock:
            sock.user_data["bound_streams"] = \
                sock.user_data.get("bound_streams", 0) + 1
            if prev is not None and \
                    not getattr(self, "_slot_released", False):
                _release_stream_slot(prev)
            self._slot_released = False   # the new sock holds a slot
        if prev is sock:
            self.socket = sock
            return
        if prev is not None:
            try:
                prev.off_failed(self._on_socket_failed)
            except AttributeError:
                pass
        self.socket = sock
        self._subscribed_sock = sock
        sock.on_failed(self._on_socket_failed)

    def _on_socket_failed(self, sock) -> None:
        if sock is not self.socket:
            return  # a previous attempt's socket: the stream moved on
        self._remote_close_once()

    def _remote_close_once(self) -> None:
        """Exactly-once remote-closure path shared by the peer's close
        frame and socket failure (they race on shutdown: close frame
        then connection drop is the normal sequence — on_close must not
        double-fire)."""
        with self._close_lock:
            if self.closed or self.remote_closed:
                return
            self.remote_closed = True
        # a remotely-closed stream interleaves no further frames: give
        # back the cut-through slot now — close() releases via the
        # _subscribed_sock pop, which this leaves intact for the
        # failure-subscription cleanup (release and unsubscribe are
        # separate concerns; the pop below guards double release)
        sub = getattr(self, "_subscribed_sock", None)
        if sub is not None and not getattr(self, "_slot_released", False):
            self._slot_released = True
            _release_stream_slot(sub)
        # a nonzero sentinel makes every credit park short-circuit
        # (butex value_changed), so a writer racing this close cannot
        # sleep out its full timeout on a dead stream
        self._credits.fetch_add(1 << 20)
        self._credits.wake_all()
        self._established.set()        # unblock pre-establish waiters
        self._recv_q.execute(("close", None))   # fire on_close callbacks

    # ---------------------------------------------------------------- close
    def close(self) -> None:
        with self._close_lock:
            if self.closed:
                return
            self.closed = True
        if self.socket is not None and self.peer_id and not self.remote_closed:
            try:
                self._send_frame(b"", None, close=True, data=False)
            except Exception:
                pass
        # drop the failure subscription: a long-lived multiplexed socket
        # must not keep dead streams reachable. The subscription lives
        # on _subscribed_sock, which can lag self.socket when the send
        # path plain-assigned a newer socket after binding.
        sub = getattr(self, "_subscribed_sock", None)
        if sub is not None:
            self._subscribed_sock = None
            if not getattr(self, "_slot_released", False):
                self._slot_released = True
                _release_stream_slot(sub)
            try:
                sub.off_failed(self._on_socket_failed)
            except AttributeError:
                pass
        _stream_pool.remove(self.id)
        self._credits.fetch_add(1 << 20)   # short-circuit pending parks
        self._credits.wake_all()

    def on_close(self, cb: Callable) -> None:
        self._close_cbs.append(cb)

    def join_drained(self, timeout_s: float = 5.0) -> bool:
        return self._recv_q.join(timeout_s)


def address_stream(stream_id: int) -> Optional[Stream]:
    return _stream_pool.address(stream_id)


def process_stream_frame(msg, socket) -> None:
    """Dispatch a STREAM frame (called from tpu_std.process)."""
    stream = _stream_pool.address(msg.meta.stream_settings.stream_id)
    if stream is None:
        return  # stream already closed; drop (reference drops too)
    stream._on_frame(msg)


_payload_bytes = None   # client_dispatch.PayloadBytes, bound on first use


class FastStreamMsg:
    """The turbo lane's stream-frame message: payload/attachment are
    plain bytes wearing the documented read surface (to_bytes/size via
    PayloadBytes) — no RpcMeta object, no IOBuf. ``meta`` materializes
    a pb view lazily for the rare consumer that wants it, carrying
    EVERY StreamSettings field the frame had (the classic lane's
    msg.meta does — the lanes must not observably diverge). The
    scanner upholds that contract by DEFERRING any frame whose
    StreamSettings carries a field outside this record's vocabulary
    (need_feedback=true, credits past INT32_MAX): such frames reach
    the classic lane only, so a materialized meta here is always
    faithful (fastcore.cc walk_stream_meta; pinned by
    test_stream.py::TestScannerLaneParity)."""

    __slots__ = ("payload", "attachment", "device_arrays", "_ss")

    def __init__(self, payload, attachment, sid: int, seq: int,
                 credits: int = 0, close: int = 0):
        global _payload_bytes
        if _payload_bytes is None:
            from brpc_tpu.rpc.client_dispatch import PayloadBytes
            _payload_bytes = PayloadBytes
        self.payload = _payload_bytes(payload)
        ab = IOBuf()
        if attachment:
            ab.append(attachment)
        self.attachment = ab
        # frames carrying device payloads always take the classic path
        # (the scanner defers them), so this lane's is empty by contract
        self.device_arrays: list = []
        self._ss = (sid, seq, credits, close)

    @property
    def meta(self):
        m = pb.RpcMeta()
        ss = m.stream_settings
        ss.stream_id = self._ss[0]
        if self._ss[1]:
            ss.frame_seq = self._ss[1]
        if self._ss[2]:
            ss.credits = self._ss[2]
        if self._ss[3]:
            ss.close = True
        return m


def process_stream_frame_fast(sid: int, seq: int, credits: int, close: int,
                              payload: bytes, att: bytes) -> None:
    """Dispatch a scan_frames stream record (turbo lane): the inlined
    twin of Stream._on_frame — keep their semantics in lockstep."""
    stream = _stream_pool.address(sid)
    if stream is None:
        return  # stream already closed; drop (reference drops too)
    if credits:
        stream._credits.fetch_add(credits)
        stream._credits.wake_all()
    if close:
        stream._remote_close_once()
        return
    if seq:  # DATA frame (possibly empty payload)
        stream._recv_q.execute(("frame", FastStreamMsg(payload, att, sid,
                                                       seq, credits,
                                                       close)))


# ------------------------------------------------------------- establishment
def stream_accept(cntl, options: Optional[StreamOptions] = None) -> Optional[Stream]:
    """Server side: accept the stream the client attached to this RPC
    (StreamAccept). Must be called inside the handler."""
    peer_id = getattr(cntl, "_peer_stream_id", 0)
    if not peer_id:
        return None
    s = Stream(options)
    s.peer_id = peer_id
    s.bind_socket(cntl._server_socket)
    s._on_established()
    cntl._accepted_stream = s
    return s
