"""ProgressiveAttachment: stream an unbounded HTTP response body in
chunks after the RPC handler returns (progressive_attachment.{h,cpp} +
progressive_reader.h in the reference).

Server handler usage:
    @svc.method()
    def Download(cntl, request):
        pa = cntl.create_progressive_attachment()
        def feed():
            for block in blocks:
                pa.write(block)
            pa.close()
        threading.Thread(target=feed).start()   # or a fiber
        return None       # body comes from the attachment

The HTTP layer sends ``Transfer-Encoding: chunked`` headers and the
attachment writes chunks to the connection; close() sends the
terminating 0-chunk. All state transitions (buffer -> bound -> closed)
happen under one lock so a feeder racing _bind can never reorder chunks
or emit the terminator before buffered data. ``wait_finished`` lets the
HTTP drain fiber hold the connection until the body is complete —
pipelined requests behind a progressive response must not interleave.
(The tpu_std-native equivalent of unbounded transfer is the credit-based
Stream — this is the curl-compatible path.)"""

from __future__ import annotations

import threading
from typing import List, Optional

from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.fiber.sync import FiberEvent


class ProgressiveAttachment:
    def __init__(self, content_type: str = "application/octet-stream"):
        self.content_type = content_type
        self._lock = threading.Lock()
        self._socket = None
        self._pending: List[bytes] = []
        self._closed = False
        self._failed = False            # peer gone (socket on_failed)
        self._finished = FiberEvent()   # terminator written (or conn dead)

    # ----------------------------------------------------- handler side
    def write(self, data) -> bool:
        """Queue/send one chunk; False once closed or the peer is gone.
        A feeder streaming an unbounded body MUST watch this: after the
        bound connection fails, every further write reports False so
        the producer can stop (and release whatever generates the
        body) instead of feeding a dead socket forever."""
        data = bytes(data)
        if not data:
            with self._lock:
                return not self._closed and not self._failed
        with self._lock:
            if self._closed or self._failed:
                return False
            if self._socket is None:
                self._pending.append(data)
                return True
            # chunk write under the lock: serializes against _bind's
            # pending flush and close's terminator (socket.write only
            # enqueues, so holding the lock is cheap)
            return self._write_chunk(self._socket, data)

    def close(self) -> None:
        """Terminate the body (0-length chunk). Idempotent."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            if self._socket is None:
                return      # _bind sends the terminator after the flush
            self._send_terminator(self._socket)
        self._finished.set()

    @property
    def closed(self) -> bool:
        return self._closed

    # -------------------------------------------------------- http side
    def _bind(self, socket) -> None:
        """Called by the HTTP layer after response headers are written:
        flush buffered chunks — and the terminator if already closed —
        atomically, so concurrent write()/close() order behind us."""
        with self._lock:
            self._socket = socket
            for data in self._pending:
                self._write_chunk(socket, data)
            self._pending = []
            done = self._closed
            if done:
                self._send_terminator(socket)
        socket.on_failed(self._on_socket_failed)
        if done:
            self._finished.set()

    def _on_socket_failed(self, _sock) -> None:
        """The bound connection died: latch the failure under the lock
        (write() must observably flip to False — a feeder racing this
        is mid-write and picks it up next chunk) and release waiters."""
        with self._lock:
            self._failed = True
        self._finished.set()

    async def wait_finished(self) -> None:
        """Await body completion (terminator sent or connection dead)."""
        await self._finished.wait()

    @staticmethod
    def _send_terminator(socket) -> None:
        buf = IOBuf()
        buf.append(b"0\r\n\r\n")
        # graftlint: disable=callback-under-lock -- _lock serializes
        # chunk framing with the _failed latch (the dead-peer fix);
        # Socket.write only queues, and the failure path flips a flag
        socket.write(buf)

    @staticmethod
    def _write_chunk(socket, data: bytes) -> bool:
        buf = IOBuf()
        buf.append(f"{len(data):x}\r\n".encode())
        buf.append(data)
        buf.append(b"\r\n")
        # graftlint: disable=callback-under-lock -- same discipline as
        # _send_terminator: framing order IS what _lock protects
        return socket.write(buf)
