"""ProgressiveAttachment: stream an unbounded HTTP response body in
chunks after the RPC handler returns (progressive_attachment.{h,cpp} +
progressive_reader.h in the reference).

Server handler usage:
    @svc.method()
    def Download(cntl, request):
        pa = cntl.create_progressive_attachment()
        def feed():
            for block in blocks:
                pa.write(block)
            pa.close()
        threading.Thread(target=feed).start()   # or a fiber
        return None       # body comes from the attachment

The HTTP layer sends ``Transfer-Encoding: chunked`` headers and the
attachment writes chunks straight to the connection; close() sends the
terminating 0-chunk and keeps the connection alive. (The tpu_std-native
equivalent of unbounded transfer is the credit-based Stream — this is
the curl-compatible path.)"""

from __future__ import annotations

import threading
from typing import List, Optional

from brpc_tpu.butil.iobuf import IOBuf


class ProgressiveAttachment:
    def __init__(self, content_type: str = "application/octet-stream"):
        self.content_type = content_type
        self._lock = threading.Lock()
        self._socket = None
        self._pending: List[bytes] = []
        self._closed = False
        self._sent_terminator = False

    # ----------------------------------------------------- handler side
    def write(self, data) -> bool:
        """Queue/send one chunk; False once closed or the peer is gone."""
        data = bytes(data)
        if not data:
            return not self._closed
        with self._lock:
            if self._closed:
                return False
            if self._socket is None:
                self._pending.append(data)
                return True
            socket = self._socket
        return self._write_chunk(socket, data)

    def close(self) -> None:
        """Terminate the body (0-length chunk). Idempotent."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            socket = self._socket
            if socket is None:
                return      # _bind sends the terminator after the flush
            self._sent_terminator = True
        buf = IOBuf()
        buf.append(b"0\r\n\r\n")
        socket.write(buf)

    @property
    def closed(self) -> bool:
        return self._closed

    # -------------------------------------------------------- http side
    def _bind(self, socket) -> None:
        """Called by the HTTP layer after response headers are written:
        flush buffered chunks, and the terminator if already closed."""
        with self._lock:
            self._socket = socket
            pending, self._pending = self._pending, []
            need_term = self._closed and not self._sent_terminator
            if need_term:
                self._sent_terminator = True
        for data in pending:
            self._write_chunk(socket, data)
        if need_term:
            buf = IOBuf()
            buf.append(b"0\r\n\r\n")
            socket.write(buf)

    @staticmethod
    def _write_chunk(socket, data: bytes) -> bool:
        buf = IOBuf()
        buf.append(f"{len(data):x}\r\n".encode())
        buf.append(data)
        buf.append(b"\r\n")
        return socket.write(buf)
