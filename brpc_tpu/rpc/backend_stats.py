"""Per-backend client telemetry: the measurement substrate under the
cluster fabric (LALB, adaptive concurrency, budget-aware hedging).

Every observability layer so far watches the SERVER side; this module
watches the CLIENT's view of the cluster. Each (channel, backend
endpoint) pair owns a stat cell — decayed qps, latency EWMA plus
pooled-sample percentiles (bvar/percentile.py reservoirs, never
averaged percentiles), an inflight gauge, error counts by errno class,
bytes in/out — updated from the channel's attempt lifecycle:

  attempt_start     an attempt was issued at a backend (inflight+1)
  attempt_error     an intermediate attempt failed there (retry moves
                    on; latency observed, error classed)
  call_complete     the call reached its verdict: the responding
                    backend gets the final observation, every other
                    still-open selection (a backup that lost the race)
                    is abandoned — inflight returns without polluting
                    latency stats, mirroring LoadBalancer.abandon

The cells live in a MultiDimension labeled (channel, backend), so the
prometheus dump renders proper ``backend_stats_*{channel=..,backend=..}``
series. The page builders here (``backends_page_payload``,
``lb_trace_payload``) are shared by the HTTP routes and the builtin RPC
service, so the two views cannot diverge; rows carry bounded raw
latency samples so ``tools/cluster_top.py`` can pool percentiles
across nodes (the ShardAggregator discipline, cross-node).

The LB decision ring is a bounded per-channel deque of
select/feedback/abandon/exclude/health/naming events recording WHY each
backend was chosen or skipped (exclusion sets, breaker isolation,
locality-aware weight factors), served at ``/lb_trace?channel=``.

Cost gating: ``BRPC_TPU_BACKEND_STATS=0`` (env, read at import) or the
runtime flag ``backend_stats_enabled`` turns the whole layer into one
flag check per call — the bench's ``backend_stats_overhead_pct``
headline key is exactly on-vs-off qps.
"""

from __future__ import annotations

import os
import threading
import time
import weakref
from collections import deque
from typing import Dict, List, Optional, Tuple

from brpc_tpu.butil.endpoint import EndPoint
from brpc_tpu.butil.fast_rand import fast_rand_less_than
from brpc_tpu.butil.flags import define_flag, flag as _flag
from brpc_tpu.bvar.multi_dimension import MultiDimension
from brpc_tpu.bvar.reducer import Adder
from brpc_tpu.bvar.variable import Variable
from brpc_tpu.bvar.window import PerSecond
from brpc_tpu.rpc import errno_codes as berr

define_flag("backend_stats_enabled",
            os.environ.get("BRPC_TPU_BACKEND_STATS", "1") != "0",
            "per-backend client stat cells + LB decision ring "
            "(/backends, /lb_trace); BRPC_TPU_BACKEND_STATS=0 sets the "
            "default off for overhead A/B runs")
define_flag("lb_trace_ring", 256,
            "events kept per channel in the LB decision ring "
            "(/lb_trace)", validator=lambda v: v >= 16)

# cells are keyed by operator-meaningful names, but a runaway caller
# (a channel constructed per request) must degrade to a bounded table,
# not an unbounded registry — overflow lands on one catch-all cell
MAX_CELLS = 4096
_OVERFLOW_KEY = ("_overflow", "_overflow")

EWMA_ALPHA = 0.2

# overload-rejection error classes (DAGOR discipline): the backend
# answered "I'm shedding", in microseconds — a reject must neither
# pollute latency telemetry (EWMA/reservoir) nor be mistaken for
# breakage (LALB error penalty, circuit breaker). ERPCTIMEDOUT joins
# the class only when a server RESPONDED with it (the deadline shed
# gate) — a client-local timeout has no responder and stays a failure.
# EPRIORITYSHED (the priority-admission shed, ISSUE 14) is a member
# whether the SERVER shed it or the CLIENT failed it fast against the
# piggybacked threshold: neither flavor burned anything anywhere, so
# it must not drain retry tokens, darken the channel, or penalize the
# balancer — the PR 10 ELIMIT rule.
REJECT_CODES = frozenset({berr.ELIMIT, berr.EOVERCROWDED,
                          berr.EPRIORITYSHED})


def is_reject(code: int, responded_server=None) -> bool:
    return code in REJECT_CODES or (
        code == berr.ERPCTIMEDOUT and responded_server is not None)


def enabled() -> bool:
    return _flag("backend_stats_enabled")


def ep_key(ep) -> str:
    """Canonical backend row key: scheme://host:port, extras stripped —
    a naming entry ``tcp://a:1#w=3`` and the socket's remote endpoint
    ``tcp://a:1`` must land on ONE row."""
    if isinstance(ep, EndPoint):
        port = f":{ep.port}" if ep.port else ""
        return f"{ep.scheme}://{ep.host}{port}"
    return str(ep)


class BackendCell(Variable):
    """One (channel, backend) stat cell. Counter discipline: every
    ``attempts`` increment is matched by exactly one ``completed`` or
    ``abandoned`` increment (the chaos test's attribution invariant);
    ``connect_errors`` count selections that never became an issued
    attempt (refused connects) and sit outside that balance.

    The update paths sit on EVERY client attempt, so the cell keeps
    its own reservoir + sum/max under ONE lock instead of composing a
    LatencyRecorder (whose four thread-safe sub-recorders cost ~4x in
    calls alone); the one thing a composed bvar still buys — decayed
    qps — rides a single Adder + PerSecond window."""

    SAMPLE_CAP = 512

    __slots__ = ("_lock", "_count_var", "_qps", "ewma_us", "inflight",
                 "attempts", "completed", "abandoned", "connect_errors",
                 "rejects", "errors", "bytes_in", "bytes_out", "_samples",
                 "_nsampled", "_sum_us", "_max_us")

    def __init__(self):
        super().__init__()
        self._lock = threading.Lock()
        self._count_var = Adder(0)
        self._qps = PerSecond(self._count_var)
        self.ewma_us = 0.0
        self.inflight = 0
        self.attempts = 0
        self.completed = 0
        self.abandoned = 0
        self.connect_errors = 0
        self.rejects = 0
        self.errors: Dict[str, int] = {}
        self.bytes_in = 0
        self.bytes_out = 0
        self._samples: List[float] = []
        self._nsampled = 0
        self._sum_us = 0.0
        self._max_us = 0.0

    # ------------------------------------------------------------ updates
    def on_start(self, nbytes_out: int) -> None:
        with self._lock:
            self.inflight += 1
            self.attempts += 1
            self.bytes_out += nbytes_out

    def on_feedback(self, latency_us: float, failed: bool, code: int,
                    nbytes_in: int = 0) -> None:
        with self._lock:
            if self.inflight > 0:
                self.inflight -= 1
            self.completed += 1
            self.bytes_in += nbytes_in
            self._sum_us += latency_us
            if latency_us > self._max_us:
                self._max_us = latency_us
            # bounded reservoir (the Percentile discipline, one lock):
            # pooled on read for percentiles, shipped raw on /backends
            # rows for cross-node pooling
            n = self._nsampled
            self._nsampled = n + 1
            s = self._samples
            if len(s) < self.SAMPLE_CAP:
                s.append(latency_us)
            else:
                i = fast_rand_less_than(n + 1)
                if i < self.SAMPLE_CAP:
                    s[i] = latency_us
            ewma = self.ewma_us
            self.ewma_us = (1 - EWMA_ALPHA) * ewma \
                + EWMA_ALPHA * latency_us if ewma else latency_us
            if failed:
                cls = berr.errno_name(code)
                self.errors[cls] = self.errors.get(cls, 0) + 1
        self._count_var.add(1)     # thread-local; outside the cell lock

    def on_reject(self, code: int, nbytes_in: int = 0) -> None:
        """The backend shed this attempt (ELIMIT/EOVERCROWDED or a
        server-responded deadline shed): the error CLASS is counted so
        overload is distinguishable from breakage, but the near-zero
        reject round-trip never touches the latency EWMA/reservoir —
        a shedding node must not look FAST to the balancer."""
        with self._lock:
            if self.inflight > 0:
                self.inflight -= 1
            self.completed += 1
            self.rejects += 1
            self.bytes_in += nbytes_in
            cls = berr.errno_name(code)
            self.errors[cls] = self.errors.get(cls, 0) + 1
        self._count_var.add(1)     # thread-local; outside the cell lock

    def on_abandon(self) -> None:
        with self._lock:
            if self.inflight > 0:
                self.inflight -= 1
            self.abandoned += 1

    def on_connect_error(self, code: int) -> None:
        with self._lock:
            self.connect_errors += 1
            cls = berr.errno_name(code)
            self.errors[cls] = self.errors.get(cls, 0) + 1

    # ------------------------------------------------------------- reads
    def samples(self, limit: int = 256) -> List[float]:
        """Bounded raw latency reservoir — what cluster_top pools for
        cross-node percentiles (never averages node percentiles)."""
        with self._lock:
            return self._samples[:limit]

    def recent_p50_us(self) -> float:
        """The reservoir's median (0.0 when empty) — the hedge arming
        bar (Channel._on_backup_timer): one sorted copy of a bounded
        list, cheap enough for the rare backup-timer path."""
        with self._lock:
            s = sorted(self._samples)
        return self._pick(s, 0.5)

    @staticmethod
    def _pick(sorted_samples: List[float], ratio: float) -> float:
        if not sorted_samples:
            return 0.0
        idx = min(len(sorted_samples) - 1,
                  int(ratio * len(sorted_samples)))
        return sorted_samples[idx]

    def get_value(self) -> dict:
        with self._lock:
            nerr = sum(self.errors.values())
            observed = self.completed + self.connect_errors
            s = sorted(self._samples)
            out = {
                "attempts": self.attempts,
                "completed": self.completed,
                "abandoned": self.abandoned,
                "connect_errors": self.connect_errors,
                "rejects": self.rejects,
                "inflight": self.inflight,
                "errors": nerr,
                "error_ratio": round(nerr / observed, 4) if observed
                else 0.0,
                "latency_ewma_us": round(self.ewma_us, 1),
                "bytes_in": self.bytes_in,
                "bytes_out": self.bytes_out,
                "count": self.completed,
                # rejects complete without a latency observation: the
                # average divides by the observed completions only
                "latency_avg_us": round(
                    self._sum_us / (self.completed - self.rejects), 1)
                if self.completed > self.rejects else 0.0,
                "max_latency_us": self._max_us,
            }
            for cls, n in self.errors.items():
                out[f"errors_{cls}"] = n
        out["qps"] = self._qps.get_value()
        out["latency_p50_us"] = self._pick(s, 0.5)
        out["latency_p90_us"] = self._pick(s, 0.9)
        out["latency_p99_us"] = self._pick(s, 0.99)
        return out


class _BackendDim(MultiDimension):
    """The labeled family, with a JSON-safe get_value: /vars dumps call
    json.dumps on the value and tuple keys would raise — the prometheus
    dumper reads labels through ``labeled_items()`` instead, so the
    (channel, backend) labels stay intact there."""

    def get_value(self) -> Dict[str, object]:
        with self._lock:
            items = list(self._stats.items())
        return {"|".join(k): v.get_value() for k, v in items}


class BackendStats:
    """Process-wide registry: the labeled cell family, the per-channel
    decision rings, and weak back-refs to the owning channels (for
    breaker/health/naming state on the page)."""

    def __init__(self):
        self._dim = _BackendDim(("channel", "backend"), BackendCell)
        self._rings: Dict[str, deque] = {}
        self._ring_lock = threading.Lock()
        self._channels: "weakref.WeakValueDictionary[str, object]" = \
            weakref.WeakValueDictionary()
        self.unattributed = 0           # verdicts with no attributable row

    # ------------------------------------------------------------- cells
    def cell(self, channel: str, backend: str) -> BackendCell:
        key = (channel, backend)
        if not self._dim.has_stats(key) \
                and self._dim.count_stats() >= MAX_CELLS:
            key = _OVERFLOW_KEY
        return self._dim.get_stats(key)

    def rows(self) -> List[Tuple[Tuple[str, str], BackendCell]]:
        return [(k, self._dim.get_stats(k))
                for k in self._dim.list_stats()]

    # -------------------------------------------------------------- ring
    def ring(self, channel: str) -> deque:
        want = _flag("lb_trace_ring")
        with self._ring_lock:
            r = self._rings.get(channel)
            if r is None or r.maxlen != want:
                r = deque(r or (), maxlen=want)
                self._rings[channel] = r
            return r

    def ring_names(self) -> Dict[str, int]:
        with self._ring_lock:
            return {n: len(r) for n, r in self._rings.items()}

    # ---------------------------------------------------------- channels
    def register_channel(self, name: str, owner) -> None:
        self._channels[name] = owner

    def channel_owner(self, name: str):
        return self._channels.get(name)


_registry: Optional[BackendStats] = None
_registry_lock = threading.Lock()


def global_stats() -> BackendStats:
    global _registry
    reg = _registry
    if reg is None:
        with _registry_lock:
            if _registry is None:
                _registry = BackendStats()
                _registry._dim.expose("backend_stats")
            reg = _registry
    return reg


def expose_backend_vars() -> None:
    """(Re-)expose the labeled family — called from Server.start like
    the socket counters, surviving a test fixture's unexpose_all."""
    global_stats()._dim.expose("backend_stats")


def _postfork_reset() -> None:
    """Fork hygiene: every cell and ring event describes PARENT-side
    client traffic on sockets the child does not own; a forked shard
    starts its cluster view from zero."""
    global _registry, _registry_lock
    _registry = None
    _registry_lock = threading.Lock()


from brpc_tpu.butil import postfork  # noqa: E402  (registration ships
#                                      with the singleton it resets)

postfork.register("rpc.backend_stats", _postfork_reset)


def _backend_census() -> dict:
    """Resource census: cells + buffered ring events, with the
    reservoir samples as the byte-denominated cost (the elastic part —
    a leaking per-request channel shows up here as runaway cells)."""
    reg = _registry
    if reg is None:
        return {"count": 0, "events": 0, "bytes": 0}
    nbytes = 0
    for _, cell in reg.rows():
        nbytes += len(cell.samples(1024)) * 8
    events = sum(reg.ring_names().values())
    return {"count": reg._dim.count_stats(), "events": events,
            "bytes": nbytes + events * 200}


from brpc_tpu.butil import resource_census as _census  # noqa: E402
#   (census registration ships with the registry it measures)

_census.register("backend_stats", _backend_census)


# --------------------------------------------------- attempt accounting
#
# Per-call open-attempt records ride the controller
# (``cntl._bs_attempts``: [backend_key, start_ns, cell] triples —
# the cell rides the record so the hot completion paths never touch
# the registry) under the controller's ``_arb_lock`` (an RLock the
# call path has already materialized; the failure paths may hold it
# when they land here, which is exactly why it must be re-entrant).

def attempt_start(cntl, rec: list, hook=None) -> None:
    """Record an opened attempt (the channel resolved the cell and
    stamped the record — see Channel._bs_attempt_begin). ``hook`` is
    the channel's completion sweep, registered under the SAME lock
    hold (one RLock round trip per attempt, and the registration is
    completion-aware like Controller._add_complete_hook — a hook that
    missed the window runs immediately so the record cannot leak)."""
    run_now = False
    with cntl._arb_lock:
        cntl.__dict__.setdefault("_bs_attempts", []).append(rec)
        if hook is not None:
            if not cntl._completed:
                hooks = cntl._complete_hooks
                if hook not in hooks:
                    hooks.append(hook)
            else:
                run_now = True
    if run_now:
        try:
            hook(cntl)
        except Exception:
            pass


def attempt_error(channel: str, cntl, code: int, ep=None) -> None:
    """An intermediate attempt failed. Pops the matching open record
    (by endpoint when the failure path knows it — with a concurrent
    backup the last record may belong to a different, healthy backend);
    a failure with NO open record (a connect that never issued) is
    classed on the right row as a connect error."""
    key = ep_key(ep) if ep is not None else None
    rec = None
    with cntl._arb_lock:
        recs = cntl.__dict__.get("_bs_attempts")
        if recs:
            if key is not None:
                for r in reversed(recs):
                    if r[0] == key:
                        rec = r
                        break
            if rec is None:
                rec = recs[-1]
            recs.remove(rec)
    if rec is None:
        reg = global_stats()
        if key is not None:
            reg.cell(channel, key).on_connect_error(code)
        else:
            reg.unattributed += 1
        return
    if code in REJECT_CODES:
        rec[2].on_reject(code)
        return
    lat_us = (time.monotonic_ns() - rec[1]) / 1e3
    rec[2].on_feedback(lat_us, True, code)


def call_complete(cntl) -> None:
    """The call reached its verdict: the record matching the backend
    whose response completed the call (or the last attempt, for
    timeouts/failures with no responder) gets the final observation;
    every other open record is a losing backup/stale retry and is
    abandoned. Cancellation (ECANCELED) is client-local — no backend
    failed and the truncated latency is meaningless, so every open
    record abandons, mirroring the cluster channel's LB sweep."""
    d = cntl.__dict__
    with cntl._arb_lock:
        recs = d.pop("_bs_attempts", None)
    if not recs:
        return
    if cntl.error_code == berr.ECANCELED:
        for rec in recs:
            rec[2].on_abandon()
        return
    winner = recs[-1]
    if len(recs) > 1:
        ep = cntl.responded_server
        if ep is not None:
            key = ep_key(ep)
            for r in reversed(recs):
                if r[0] == key:
                    winner = r
                    break
    code = cntl.error_code
    if is_reject(code, cntl.responded_server):
        winner[2].on_reject(code, d.get("_bs_resp_bytes", 0))
    else:
        lat_us = (time.monotonic_ns() - winner[1]) / 1e3
        winner[2].on_feedback(lat_us, cntl.failed(), code,
                              d.get("_bs_resp_bytes", 0))
    if len(recs) > 1:
        for rec in recs:
            if rec is not winner:
                rec[2].on_abandon()


# ----------------------------------------------------- LB decision ring

def _ep_list(eps, limit: int = 8) -> List[str]:
    out = [ep_key(e) for e in list(eps)[:limit]]
    more = len(eps) - len(out)
    if more > 0:
        out.append(f"+{more} more")
    return out


def ring_event(channel: str, kind: str, ring: Optional[deque] = None,
               **fields) -> None:
    """Append one decision event. Callers on the per-call hot path
    pass their cached ``ring`` deque (Channel._bs_ring) to skip the
    registry lock; deque.append is itself thread-safe."""
    if not enabled():
        return
    fields["t"] = round(time.time(), 3)
    fields["kind"] = kind
    if ring is None:
        ring = global_stats().ring(channel)
    ring.append(fields)


def lb_trace_payload(channel: Optional[str],
                     n: int = 100) -> Optional[dict]:
    """The /lb_trace payload: one channel's recent decision events
    (oldest first), or — with no channel named — the channel
    directory. None = unknown channel (the route 404s)."""
    reg = global_stats()
    if not channel:
        return {"channels": reg.ring_names(),
                "hint": "/lb_trace?channel=<name>&n=<events>"}
    with reg._ring_lock:
        r = reg._rings.get(channel)
        events = list(r)[-n:] if r is not None else None
    if events is None:
        return None
    return {"channel": channel, "events": events}


# ------------------------------------------------------------ the page

def backends_page_payload(samples: int = 256) -> dict:
    """The /backends payload, shared by the HTTP route and the builtin
    RPC service. Rows group by channel; each carries the cell's
    counters plus breaker/health/naming state resolved from the owning
    channel (weakly held — a closed channel's rows stay, its state
    goes ``unknown``), and a bounded raw latency reservoir for
    cross-node pooling (tools/cluster_top.py)."""
    reg = global_stats()
    channels: Dict[str, dict] = {}
    totals = {"attempts": 0, "completed": 0, "errors": 0, "inflight": 0,
              "abandoned": 0, "connect_errors": 0, "rejects": 0}
    for (ch_name, backend), cell in reg.rows():
        entry = channels.get(ch_name)
        if entry is None:
            owner = reg.channel_owner(ch_name)
            rb = getattr(owner, "_retry_budget", None)
            entry = channels[ch_name] = {
                "lb": getattr(owner, "lb_name", None)
                if owner is not None else None,
                "naming": owner.naming_info()
                if hasattr(owner, "naming_info") else None,
                # the channel's retry token bucket (retry_tokens et al);
                # None = no budget configured
                "retry_budget": rb.snapshot() if rb is not None else None,
                "backends": {},
            }
        row = cell.get_value()
        row["latency_samples"] = cell.samples(samples)
        owner = reg.channel_owner(ch_name)
        if owner is not None and hasattr(owner, "backend_state"):
            try:
                row["state"] = owner.backend_state(backend)
            except Exception:
                row["state"] = {"error": "state provider failed"}
        entry["backends"][backend] = row
        for k in totals:
            totals[k] += row.get(k, 0)
    # channel-group retry budgets (ISSUE 14): one bucket per
    # budget_group, shared by every member channel — surfaced beside
    # the per-channel buckets so an operator sees the cluster-wide
    # retry fuel, not N identical-looking private snapshots
    from brpc_tpu.rpc.retry_policy import budget_group_snapshot
    return {
        "enabled": enabled(),
        "channels": channels,
        "totals": totals,
        "budget_groups": budget_group_snapshot(),
        "unattributed_errors": reg.unattributed,
    }
