"""rpc_dump: sampled request recording for offline replay
(brpc/rpc_dump.h:50-95 + tools/rpc_replay — SURVEY.md §5 checkpoint/
resume analog). Enable by setting the ``rpc_dump_dir`` flag; a bounded
per-second sample of inbound requests is appended as JSONL
({service, method, payload(b64), log_id, ts}); tools/rpc_replay.py
re-issues them at a target QPS."""

from __future__ import annotations

import base64
import json
import os
import threading
import time
from typing import Optional

from brpc_tpu.butil.flags import define_flag, flag

define_flag("rpc_dump_dir", "", "directory for sampled request dumps "
            "(empty = disabled)")
define_flag("rpc_dump_max_requests_per_second", 100,
            "sampling budget per second", validator=lambda v: v >= 1)


class RpcDumper:
    def __init__(self):
        self._lock = threading.Lock()
        self._fh = None
        self._dir = None
        self._second = 0
        self._taken = 0

    def maybe_dump(self, service: str, method: str, payload: bytes,
                   log_id: int = 0) -> bool:
        d = flag("rpc_dump_dir")
        if not d:
            return False
        now = int(time.time())
        with self._lock:
            if now != self._second:
                self._second, self._taken = now, 0
            if self._taken >= flag("rpc_dump_max_requests_per_second"):
                return False
            self._taken += 1
            if self._fh is None or self._dir != d:
                os.makedirs(d, exist_ok=True)
                path = os.path.join(d, f"rpc_dump.{os.getpid()}.jsonl")
                self._fh = open(path, "a")
                self._dir = d
            self._fh.write(json.dumps({
                "service": service, "method": method,
                "payload": base64.b64encode(payload).decode(),
                "log_id": log_id, "ts": time.time(),
            }) + "\n")
            self._fh.flush()
        return True


global_dumper = RpcDumper()


def _postfork_reset() -> None:
    """Fork hygiene: the dump file is keyed by pid — a forked worker
    inheriting the parent's fh would interleave into the parent-pid
    file through the shared offset; its lock may be held by a dead
    thread. Fresh lock, lazily reopened per-pid file."""
    global_dumper._lock = threading.Lock()
    fh, global_dumper._fh = global_dumper._fh, None
    global_dumper._dir = None
    if fh is not None:
        try:
            fh.close()
        except Exception:
            pass


from brpc_tpu.butil import postfork as _postfork  # noqa: E402
#   (registration ships with the dumper it resets)

_postfork.register("rpc.rpc_dump", _postfork_reset)


def load_dump(path: str):
    """Yield (service, method, payload_bytes, log_id) records."""
    with open(path) as f:
        for line in f:
            if not line.strip():
                continue
            rec = json.loads(line)
            yield (rec["service"], rec["method"],
                   base64.b64decode(rec["payload"]), rec.get("log_id", 0))
