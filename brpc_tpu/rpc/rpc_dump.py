"""rpc_dump: back-compat shim over the traffic capture engine.

The seed-era sampler (bounded per-second JSONL dumps keyed by the
``rpc_dump_dir`` flag) grew into ``brpc_tpu/traffic/`` — a production
recorder with per-method sampling, rotation, a disk budget, and an
indexed recordio corpus format (.brpccap). This module keeps the old
surface alive:

  * the ``rpc_dump_dir`` / ``rpc_dump_max_requests_per_second`` flags
    still work — an active ``rpc_dump_dir`` auto-starts the capture
    recorder with the legacy budget (traffic/capture.py reads them);
  * ``global_dumper.maybe_dump(...)`` still records (now into the
    corpus format, through the recorder's sampling gates);
  * ``load_dump(path)`` still yields (service, method, payload,
    log_id) — from legacy JSONL files AND .brpccap corpora alike.

See docs/traffic.md and migrating_from_brpc.md for the new knobs.
"""

from __future__ import annotations

import base64
import json

from brpc_tpu.butil.flags import define_flag
from brpc_tpu.butil.recordio import MAGIC as _RIO_MAGIC

define_flag("rpc_dump_dir", "", "LEGACY alias: directory for sampled "
            "request capture (empty = disabled); prefer capture_dir / "
            "the /capture page")
define_flag("rpc_dump_max_requests_per_second", 100,
            "LEGACY alias: sampling budget per second (applies when "
            "capture starts via rpc_dump_dir)", validator=lambda v: v >= 1)


class RpcDumper:
    """API-compatible wrapper: forwards into the traffic recorder.
    Stateless — the recorder owns files, queueing and fork hygiene."""

    def maybe_dump(self, service: str, method: str, payload: bytes,
                   log_id: int = 0) -> bool:
        from brpc_tpu.traffic.capture import global_recorder
        rec = global_recorder()
        if not rec.capture_enabled():
            return False
        r = rec.sample_request(f"{service}.{method}", service, method,
                               bytes(payload), None, 0, 0.0, log_id, 0)
        if r is None:
            return False
        rec.record_complete(r, 0, 0.0)
        return True


global_dumper = RpcDumper()


def load_dump(path: str):
    """Yield (service, method, payload_bytes, log_id) records from a
    legacy JSONL dump or a .brpccap corpus (sniffed by magic, so old
    scripts keep working on new captures)."""
    with open(path, "rb") as f:
        head = f.read(4)
    if head == _RIO_MAGIC:
        from brpc_tpu.traffic.corpus import CorpusReader
        for rec in CorpusReader(path):
            yield (rec.service, rec.method, rec.payload, rec.log_id)
        return
    with open(path) as f:
        for line in f:
            if not line.strip():
                continue
            rec = json.loads(line)
            yield (rec["service"], rec["method"],
                   base64.b64decode(rec["payload"]), rec.get("log_id", 0))
