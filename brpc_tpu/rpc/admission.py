"""DAGOR-grade priority admission (ISSUE 14): cost-weighted limiter
slots and two-level business+user priority shedding with the threshold
fed back to senders.

The design follows WeChat's DAGOR overload control (Zhou et al.,
SoCC'18) grafted onto the server's existing overload organs (PR 10's
concurrency limiter + queue-delay gate):

* **Admission level** — every request maps to one integer:
  ``level = business_priority << 7 | user_slot`` where the business
  priority is the wire's ``RpcRequestMeta.priority`` tag and the user
  sub-priority is a stable hash of the caller's identity (auth cookie
  when present, else the connection's client address). The user slice
  exists so a threshold can cut PART of a business class — and because
  it is a stable hash, one caller's requests are consistently kept or
  consistently dropped instead of randomly flapping.

* **Threshold adaptation** — while the limiter or the queue-delay gate
  reports overload, the controller raises an admission threshold each
  window so the below-threshold fraction of the CURRENT traffic
  histogram is shed at the door (µs-cheap, before parse/handler); calm
  windows relax it back toward zero. The threshold never climbs into
  the highest business class seen in the window — with uniform
  priorities (every request untagged) the floor of that class is level
  0 and admission never sheds anything, so servers without priority-
  tagged traffic keep their exact PR 10 behavior.

* **Cost weights** — limiter slots become weighted: a request's cost
  derives from its size and its method's expected-latency bucket (fed
  from the server's per-method latency reservoirs), so a 4MB streaming
  call no longer draws the same admission slot as a 4B echo
  (``ServerOptions(request_costs=True)``).

The current threshold piggybacks on ``RpcResponseMeta.
admission_threshold`` (default-absent); clients cache it per
(backend, service) and fail doomed sends fast locally with periodic
probe-through — overload stops burning sockets and retry tokens at
the source (rpc/channel.py holds the client half).

``BRPC_TPU_ADMISSION=0`` (env, read at import) or the runtime flag
``admission_enabled`` turns the layer off for overhead A/B runs.
"""

from __future__ import annotations

import os
import threading
import time
import zlib
from typing import Optional

from brpc_tpu.butil.flags import define_flag, flag as _flag

define_flag("admission_enabled",
            os.environ.get("BRPC_TPU_ADMISSION", "1") != "0",
            "DAGOR priority-admission layer (two-level threshold "
            "shedding + response piggyback); BRPC_TPU_ADMISSION=0 sets "
            "the default off for overhead A/B runs")

USER_SLOTS = 128            # user sub-priority space per business class
LEVEL_SHIFT = 7             # level = priority << 7 | user_slot
MAX_PRIORITY = 127


def admission_enabled() -> bool:
    return _flag("admission_enabled")


def user_slot(identity) -> int:
    """Stable user sub-priority in [0, 127] from a caller identity
    (auth cookie / client address string). crc32, not hash():
    PYTHONHASHSEED salts str hashing per process, and the client must
    compute the SAME slot the server does (the racelane lesson)."""
    if not identity:
        return 0
    if isinstance(identity, str):
        identity = identity.encode("utf-8", "surrogatepass")
    return zlib.crc32(identity) & (USER_SLOTS - 1)


def cached_socket_slot(socket, ep) -> int:
    """The user sub-priority of a connection identity, cached on the
    socket: ``ep`` is whichever endpoint of the pair names the CLIENT
    (the server hashes its ``remote_endpoint``, the client its
    socket's ``local_endpoint`` — the same address, so both sides
    compute the same slot; the piggyback fail-fast depends on the
    match). ONE implementation on purpose: a drift between the two
    sides would silently turn every doomed-send decision wrong."""
    slot = socket.__dict__.get("_adm_user_slot")
    if slot is None:
        from brpc_tpu.rpc.backend_stats import ep_key
        slot = user_slot(ep_key(ep)) if ep is not None else 0
        socket._adm_user_slot = slot
    return slot


def compose_level(priority: int, slot: int) -> int:
    """One admission integer from (business priority, user slot):
    higher = more important; the business class dominates."""
    if priority < 0:
        priority = 0
    elif priority > MAX_PRIORITY:
        priority = MAX_PRIORITY
    return (priority << LEVEL_SHIFT) | (slot & (USER_SLOTS - 1))


class CostModel:
    """Weighted request cost for the concurrency limiter: cost =
    latency-bucket weight of the method (its reservoir p50, refreshed
    at most once a second) + a bytes term, capped. A weight-1 request
    is the PR 10 slot; heavier classes draw proportionally more of the
    limit, so weighted inflight tracks real pressure instead of a
    request count."""

    UNIT_BYTES = 64 * 1024          # one extra slot per 64KB
    MAX_COST = 64.0
    REFRESH_S = 1.0
    # method p50 (us) -> extra latency weight (expected-service cost)
    LAT_BUCKETS = ((1_000.0, 0.0), (10_000.0, 1.0),
                   (100_000.0, 3.0), (float("inf"), 7.0))

    def __init__(self, server):
        import weakref
        # weak: the server owns this model — a strong back-ref would
        # make the pair uncollectable as a cycle through options
        self._server_ref = weakref.ref(server)
        self._method_weights: dict = {}
        self._next_refresh = 0.0

    def request_cost(self, method_key: Optional[str], nbytes: int) -> float:
        now = time.monotonic()
        if now >= self._next_refresh:
            self._refresh_weights(now)
        cost = 1.0 + self._method_weights.get(method_key, 0.0)
        if nbytes > self.UNIT_BYTES:
            cost += nbytes / self.UNIT_BYTES
        return cost if cost <= self.MAX_COST else self.MAX_COST

    def _refresh_weights(self, now: float) -> None:
        """Re-bucket every method from its latency reservoir. Racy by
        design: concurrent refreshers compute the same table and the
        dict swap is atomic — a lock here would sit on the admission
        hot path for a once-a-second event."""
        self._next_refresh = now + self.REFRESH_S
        server = self._server_ref()
        if server is None:
            return
        weights = {}
        for key, lr in list(server.method_status.items()):
            try:
                p50 = lr.latency_percentile(0.5)
            except Exception:
                continue
            if not p50:
                continue
            for bound, w in self.LAT_BUCKETS:
                if p50 <= bound:
                    if w:
                        weights[key] = w
                    break
        self._method_weights = weights


class AdmissionController:
    """Two-level priority admission (DAGOR): windowed threshold over
    composed (business, user) levels.

    Fast path discipline: while no overload has been signalled and no
    threshold is set, ``threshold_engaged`` is two attribute reads —
    the calm server pays nothing else. Overload signals (limiter
    rejects, queue-delay sheds) arm the controller; from then on every
    request's level feeds the window histogram and windows adapt
    ``shed_frac`` toward the overload evidence: up while signals keep
    arriving, down while calm, threshold recomputed each window as the
    histogram quantile at ``shed_frac`` — clamped BELOW the floor of
    the highest business class seen, so the top class (and therefore
    uniform-priority traffic, whose only class IS the top) is never
    shed by priority.

    ``_lock`` is a LEAF (LOCK_ORDER row): taken bare on the dispatch
    admission path, never wraps another acquisition."""

    WINDOW_S = 0.5
    MAX_SHED_FRAC = 0.95
    STEP_UP_MIN = 0.05          # overloaded window: raise at least this
    STEP_DOWN = 0.10            # calm window: relax this much
    HIST_CAP = 2048             # distinct levels tracked per window

    def __init__(self, window_s: Optional[float] = None):
        self._lock = threading.Lock()
        self._armed = False          # racy-read fast-path gate
        self._threshold = 0          # racy-read piggyback value
        self._shed_frac = 0.0
        self._hist: dict = {}
        self._win_total = 0
        self._win_over = 0
        self._win_start = time.monotonic()
        self._shed_count = 0         # lifetime priority sheds (snapshot)
        if window_s:
            self.WINDOW_S = float(window_s)

    # ------------------------------------------------------- hot path
    def threshold_engaged(self) -> bool:
        """True while admission has anything to say (armed by overload
        signals; disarmed after the threshold decays to zero through a
        calm window). Racy read by design — the dispatch path must not
        pay a lock to learn the server is calm."""
        return self._armed

    def admit_level(self, level: int) -> bool:
        """Count this request's level into the window and judge it
        against the current threshold. Only called while engaged (the
        caller checks ``threshold_engaged`` first); False = shed with
        EPRIORITYSHED before parse/handler."""
        with self._lock:
            self._tally_locked(level)
            self._maybe_adapt_locked()
            shed = level < self._threshold
            if shed:
                self._shed_count += 1
        return not shed

    # ------------------------------------------------- overload signals
    def signal_overload(self, level: int = 0,
                        counted: bool = False) -> None:
        """An overload organ rejected work this instant (concurrency
        limiter full, queue-delay gate tripped): arm the controller and
        feed the evidence the next window adapts on. Cold path — it
        only runs when the server is already shedding. ``counted`` =
        this request's level already entered the window histogram
        through ``admit_level`` (the engaged dispatch path) — tallying
        it again would double-weight rejected levels AND halve the
        over/total adaptation ratio exactly in deep overload."""
        with self._lock:
            self._armed = True
            self._win_over += 1
            if not counted:
                self._tally_locked(level)
            self._maybe_adapt_locked()

    # ------------------------------------------------------- internals
    def _tally_locked(self, level: int) -> None:
        self._win_total += 1
        h = self._hist
        n = h.get(level)
        if n is None and len(h) >= self.HIST_CAP:
            return                      # bounded: drop novel levels
        h[level] = (n or 0) + 1

    def _maybe_adapt_locked(self) -> None:
        now = time.monotonic()
        if now - self._win_start < self.WINDOW_S:
            return
        total = self._win_total
        over = self._win_over
        if over > 0 and total > 0:
            # raise the shed target by at least STEP_UP_MIN, more when
            # a large fraction of the window hit the overload organs
            # (half the observed overflow — full-step chasing
            # oscillates against the load the shed itself removes)
            step = max(self.STEP_UP_MIN, 0.5 * over / total)
            self._shed_frac = min(self.MAX_SHED_FRAC,
                                  self._shed_frac + step)
        else:
            self._shed_frac = max(0.0, self._shed_frac - self.STEP_DOWN)
        self._threshold = self._quantile_threshold_locked(total)
        if self._threshold == 0 and self._shed_frac == 0.0 and over == 0:
            self._armed = False
        self._hist = {}
        self._win_total = 0
        self._win_over = 0
        self._win_start = now

    def _quantile_threshold_locked(self, total: int) -> int:
        """Smallest T with count(levels < T) >= shed_frac * total,
        clamped below the floor of the highest business class seen —
        DAGOR never sheds its top class, and with uniform priorities
        that floor is level 0, so the threshold stays 0."""
        if self._shed_frac <= 0.0 or not total or not self._hist:
            return 0
        levels = sorted(self._hist)
        top_band_floor = (levels[-1] >> LEVEL_SHIFT) << LEVEL_SHIFT
        if top_band_floor <= 0:
            return 0
        target = self._shed_frac * total
        cum = 0
        threshold = 0
        for lvl in levels:
            if cum >= target:
                break
            threshold = lvl + 1
            cum += self._hist[lvl]
        return min(threshold, top_band_floor)

    # --------------------------------------------------------- reads
    def wire_threshold(self) -> int:
        """The piggyback value for RpcResponseMeta.admission_threshold
        (racy read; 0 = calm, field stays absent on the wire)."""
        return self._threshold

    def admission_snapshot(self) -> dict:
        with self._lock:
            return {"threshold": self._threshold,
                    "armed": self._armed,
                    "shed_frac": round(self._shed_frac, 3),
                    "priority_sheds": self._shed_count}
