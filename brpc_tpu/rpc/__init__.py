"""RPC core: Channel/Controller/Server + cluster features (SURVEY.md §2.6)."""

from brpc_tpu.rpc import errno_codes
from brpc_tpu.rpc import rpc_dump as _rpc_dump  # registers rpc_dump_* flags
from brpc_tpu.rpc.controller import Controller
from brpc_tpu.rpc.channel import Channel, ChannelOptions
from brpc_tpu.rpc.server import Server, ServerOptions
from brpc_tpu.rpc.service import Method, Service, service_from_object
from brpc_tpu.rpc.cluster_channel import ClusterChannel
from brpc_tpu.rpc.combo_channels import (
    CallMapper, ParallelChannel, PartitionChannel, PartitionParser,
    ResponseMerger, SelectiveChannel, SubCall,
)
from brpc_tpu.rpc.load_balancer import LoadBalancer, new_load_balancer
from brpc_tpu.rpc.naming import NamingService, NamingServiceThread, register_naming_service
from brpc_tpu.rpc.combo_channels import DynamicPartitionChannel
from brpc_tpu.rpc.periodic_task import PeriodicTask
from brpc_tpu.rpc.progressive import ProgressiveAttachment
from brpc_tpu.rpc.data_pool import SimpleDataPool
from brpc_tpu.rpc.auth import (
    AuthContext, AuthError, Authenticator, InterceptorError,
    TokenAuthenticator,
)

__all__ = [
    "errno_codes", "Controller", "Channel", "ChannelOptions",
    "Server", "ServerOptions", "Method", "Service", "service_from_object",
    "ClusterChannel", "CallMapper", "ParallelChannel", "PartitionChannel",
    "PartitionParser", "ResponseMerger", "SelectiveChannel", "SubCall",
    "LoadBalancer", "new_load_balancer",
    "NamingService", "NamingServiceThread", "register_naming_service",
    "AuthContext", "AuthError", "Authenticator", "InterceptorError",
    "TokenAuthenticator", "DynamicPartitionChannel", "PeriodicTask",
    "ProgressiveAttachment", "SimpleDataPool",
]
