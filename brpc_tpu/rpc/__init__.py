"""RPC core: Channel/Controller/Server + cluster features (SURVEY.md §2.6)."""

from brpc_tpu.rpc import errno_codes
from brpc_tpu.rpc.controller import Controller
from brpc_tpu.rpc.channel import Channel, ChannelOptions
from brpc_tpu.rpc.server import Server, ServerOptions
from brpc_tpu.rpc.service import Method, Service, service_from_object

__all__ = [
    "errno_codes", "Controller", "Channel", "ChannelOptions",
    "Server", "ServerOptions", "Method", "Service", "service_from_object",
]
