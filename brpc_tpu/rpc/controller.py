"""Controller: per-call context & state machine for both sides
(brpc/controller.{h,cpp}, SURVEY.md §2.6).

Client side owns: correlation id (a versioned slot in a global pool — the
bthread_id of the reference), deadline timer, retries, backup request,
response data. Completion is a one-shot event that both fibers (await) and
plain threads (block) can wait on, matching Join(cid)'s dual waiters.

Server side owns: error state, attachments, device payloads, the response
path handle.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, List, Optional

from brpc_tpu.butil.endpoint import EndPoint
from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.butil.resource_pool import ResourcePool
from brpc_tpu.fiber.sync import FiberEvent
from brpc_tpu.rpc import errno_codes as berr

# global correlation-id pool: id -> client Controller (the reference's
# bthread_id space, id.h:46). Native when available: fastcore's Pool is
# respool.cc (versioned slots, odd-version-live) holding the Controller
# objects — ids are never 0 by construction there. Resolved on FIRST
# USE, not import: fastcore.get() may compile the extension, and module
# import must stay cheap.
_call_pool = None
_call_pool_lock = threading.Lock()
_prf = None   # lazily bound client_dispatch.process_response_fast


def _pool():
    p = _call_pool
    if p is None:
        p = _make_pool()
    return p


def _make_pool():
    # locked: two first-RPC threads must agree on ONE pool — a call
    # registered in a discarded duplicate would never hear its response
    global _call_pool
    with _call_pool_lock:
        if _call_pool is None:
            from brpc_tpu.native import fastcore as _fastcore
            fc = _fastcore.get()
            if fc is not None:
                _call_pool = fc.Pool(1 << 17)
            else:
                p = ResourcePool()
                # reserve slot 0 forever: correlation id 0 must stay
                # invalid, because proto3 serializes 0 as an absent
                # field (a frame with no/zero correlation_id must never
                # address a live call)
                p.insert(None)
                _call_pool = p
        return _call_pool


def _postfork_reset() -> None:
    """Fork hygiene: every slot in the pool is a parent-side in-flight
    call whose socket/fiber the child does not own; a fresh child has
    zero calls in flight by definition."""
    global _call_pool, _call_pool_lock
    _call_pool = None
    _call_pool_lock = threading.Lock()


from brpc_tpu.butil import postfork  # noqa: E402  (registration ships
#                                      with the singleton it resets)

postfork.register("rpc.controller", _postfork_reset)


def address_call(correlation_id: int):
    return _pool().address(correlation_id)


def take_call(correlation_id: int):
    """Remove-and-return: the first finisher wins; stale responses and
    fired timers lose the race here (OnVersionedRPCReturned's version
    check, controller.cpp:575)."""
    return _pool().remove(correlation_id)


_lazy_create_lock = threading.Lock()
_MISSING = object()


class Controller:
    """Scalar fields live as CLASS defaults (an instance attribute
    appears only when written) and mutable members are created lazily on
    first touch — a Controller is built per call on BOTH sides of every
    RPC, and the reference keeps the equivalent cheap by pooling
    (resource_pool.h:14-47); in Python the analogous lever is not
    allocating the ~15 sub-objects a call never uses."""

    # ---- shared scalars
    error_code: int = berr.OK
    error_text: str = ""
    log_id: int = 0
    remote_side: Optional[EndPoint] = None
    local_side: Optional[EndPoint] = None
    auth_token: str = ""
    auth_context = None        # server side: verified peer identity
    compress_type: int = 0
    trace_id: int = 0
    span_id: int = 0
    # request priority / cost-class tag (RpcRequestMeta.priority):
    # client side set it BEFORE the call, server handlers read the
    # wire value here. 0 = unset — the tag is absent on the wire and
    # existing traffic is unchanged. Higher = more important is the
    # convention the traffic engine's per-class reports assume; the
    # DAGOR admission work will shed on it.
    request_priority: int = 0
    # ---- client side scalars
    timeout_ms: Optional[float] = None
    max_retry: Optional[int] = None   # None = inherit channel option
    backup_request_ms: Optional[float] = None
    correlation_id: int = 0
    response_payload: Optional[IOBuf] = None
    response_msg: Any = None
    _done_cb: Optional[Callable] = None
    current_try: int = 0
    start_us: int = 0
    end_us: int = 0
    used_backup: bool = False
    stream = None              # Stream piggybacked on this call
    # which server's response actually completed the call (set by
    # process_response; None on timeout/failure) — with backup
    # requests, tried_servers[-1] is NOT necessarily the winner
    responded_server = None
    _lb_swept_n: Optional[int] = None
    _owner_channel = None
    # ---- client call internals (set by Channel.call)
    _service_name: str = ""
    _method_name: str = ""
    _request_bytes: bytes = b""
    # ---- server side scalars
    _server_socket = None
    _response_sender: Optional[Callable] = None
    _progressive = None        # ProgressiveAttachment (http chunked)
    _session_local = None      # borrowed from the server's data pool
    _session_kv: Optional[dict] = None    # kvmap.h SessionKV
    # ---- deadline propagation (both sides): absolute monotonic-ns
    # deadline. Server side: stamped from the request's timeout_ms at
    # arrival (server_dispatch); client side: stamped by Channel.call —
    # retry/backup scheduling clamps to it (a retry that cannot possibly
    # complete is not issued).
    _deadline_ns: Optional[int] = None
    _completed = False         # set under _arb_lock by _complete
    _finalized = False         # _complete ran end-to-end (joiners gate)
    _issue_socket = None       # socket of the current attempt (pluck lane)

    # mutable members, created on first touch. _lb_lock guards the
    # tried/selection handshake between a late backup attempt and the
    # completion sweep; _arb_lock serializes take-and-complete /
    # take-and-retry (the reference gets this from the bthread_id lock,
    # id.h:46) — a response-error retry swaps the correlation id under
    # it so the deadline timer can never interleave with the swap.
    _LAZY = {
        "request_attachment": IOBuf,
        "response_attachment": IOBuf,
        "request_device_arrays": list,
        "response_device_arrays": list,
        "_done_event": FiberEvent,
        "_timer_ids": list,
        "tried_servers": list,      # endpoints tried (retry-elsewhere)
        "_complete_hooks": list,    # LB feedback / breaker / client spans
        "_lb_fed": list,
        "_cancel_subs": list,       # (socket, cb) notify_on_cancel subs
        "_lb_lock": threading.Lock,
        "_arb_lock": threading.RLock,
    }

    def __init__(self):
        pass

    def __getattr__(self, name):
        factory = Controller._LAZY.get(name)
        if factory is None:
            raise AttributeError(
                f"{type(self).__name__!r} object has no attribute {name!r}")
        # one global creation lock: two threads lazily materializing the
        # SAME lock field must agree on one object or arbitration breaks
        with _lazy_create_lock:
            d = self.__dict__
            v = d.get(name, _MISSING)
            if v is _MISSING:
                v = factory()
                d[name] = v
        return v

    def session_kv(self) -> dict:
        """Lazily-created per-call key/value annotations (kvmap.h +
        Controller::SessionKV): whatever the app records here is flushed
        to the log in one line when the call completes, so everything
        about one session lands greppable together. Flushing CLEARS the
        map, so on a reused controller any annotation added after the
        previous completion belongs to the NEXT call."""
        if self._session_kv is None:
            self._session_kv = {}
        return self._session_kv

    def flush_session_kv(self) -> None:
        """Log-and-clear (FlushSessionKV, controller.cpp:160: flushed at
        controller destruction; ours flushes at call completion). Never
        raises: a kv value whose __str__ explodes must not abort the
        completion path it runs on (join() would hang)."""
        kv = self._session_kv
        if not kv:
            return
        self._session_kv = None
        try:
            pairs = " ".join(f"{k}={v}" for k, v in kv.items())
            logging.getLogger("brpc_tpu.session").info(
                "Session ends. %s @%s.%s log_id=%d", pairs,
                self._service_name or "?", self._method_name or "?",
                self.log_id)
        except Exception:
            logging.getLogger("brpc_tpu.session").exception(
                "session_kv flush failed")

    def create_progressive_attachment(
            self, content_type: str = "application/octet-stream"):
        """HTTP chunked-response body fed after the handler returns
        (progressive_attachment.h); native streams use Stream instead."""
        from brpc_tpu.rpc.progressive import ProgressiveAttachment
        self._progressive = ProgressiveAttachment(content_type)
        return self._progressive

    def session_local_data(self):
        """Reusable per-request object from ServerOptions.
        session_local_data_factory (server.h session_local_data)."""
        return self._session_local

    # ---------------------------------------------------------------- names
    @property
    def service_name(self) -> str:
        return self._service_name

    @property
    def method_name(self) -> str:
        return self._method_name

    # --------------------------------------------------------------- error
    def failed(self) -> bool:
        return self.error_code != berr.OK

    def set_failed(self, code: int, text: str = "") -> None:
        self.error_code = code
        self.error_text = text or berr.errno_name(code)

    def reset_error(self) -> None:
        self.error_code = berr.OK
        self.error_text = ""

    def latency_us(self) -> int:
        return max(0, self.end_us - self.start_us)

    # ---------------------------------------------------- deadline budget
    def remaining_ms(self) -> Optional[float]:
        """Milliseconds left in this call's deadline budget, clamped at
        0.0; None when no deadline applies. Server side this is the
        CLIENT's remaining budget (arrival stamp + request timeout_ms):
        a handler past it is computing a response nobody will read —
        check it inside long loops, and nested calls the handler makes
        inherit it automatically (Channel.call shrinks their timeout to
        min(own timeout, this))."""
        dl = self._deadline_ns
        if dl is None:
            return None
        return max(0.0, (dl - time.monotonic_ns()) / 1e6)

    def deadline_expired(self) -> bool:
        """True once the deadline budget is exhausted (False when no
        deadline applies)."""
        dl = self._deadline_ns
        return dl is not None and time.monotonic_ns() >= dl

    # ---------------------------------------------------- client completion
    def _reset_for_call(self) -> None:
        """Per-CALL client state must reset on controller reuse (called
        at the top of Channel.call): a stale one-shot done event would
        make join() return before the new response arrives (with the
        previous call's payload), stale tried/attempt bookkeeping would
        exclude healthy servers or trip the cluster channel's
        late-attempt guard, a stale retry counter would shrink the new
        retry budget, and stale completion hooks (pooled-connection
        returns) would re-run and double-insert sockets into the pool.
        LB bookkeeping resets under _lb_lock — a still-in-flight backup
        attempt from the PREVIOUS call must not interleave with the
        reset and leak its selection."""
        self.reset_error()
        self.current_try = 0
        with self._arb_lock:
            self._completed = False
            self.__dict__.pop("_finalized", None)
            self._set_issue_socket(None)
            # fresh lazy event next call: a stale one-shot event would
            # make join() return with the previous call's payload
            self.__dict__.pop("_done_event", None)
        # __dict__ peeks: a FRESH controller (the common case) has no
        # instance state to reset — clearing class-default fields would
        # only materialize them
        d = self.__dict__
        d.pop("end_us", None)
        d.pop("_deadline_ns", None)        # new call, new budget
        d.pop("_pending_deadline", None)   # stale lazy deadline would
        #                                    clamp the new call's pluck
        d.pop("_pluck_fast", None)         # per-issue native-pluck hint
        d.pop("_fail_handled", None)       # per-attempt failure latch
        d.pop("_sync_fast", None)          # per-call pre-claim hint
        d.pop("_client_span", None)        # previous call's rpcz span
        d.pop("_attempt_spans", None)      # previous call's attempt spans
        d.pop("_bs_attempts", None)        # previous call's open backend
        #                                    stat-cell records (swept at
        #                                    completion; belt & braces)
        d.pop("_bs_resp_bytes", None)      # previous response's wire size
        # trace context is per-CALL: a stale trace_id would defeat the
        # serving-trace inheritance in Channel.call (the nested call
        # would chain onto the PREVIOUS request's tree) and pin every
        # reused controller to its first call's trace forever
        d.pop("trace_id", None)
        d.pop("span_id", None)
        pre = d.pop("_pluck_preclaimed", None)
        if pre is not None:                # unconsumed pre-send claim
            pre.pluck_release()
        d.pop("response_payload", None)
        d.pop("response_attachment", None)
        d.pop("response_device_arrays", None)
        d.pop("responded_server", None)
        d.pop("used_backup", None)
        d.pop("_hedge_decision", None)     # previous call's hedge arming
        d.pop("request_priority", None)    # per-call tag: a reused
        #                                    controller must not carry
        #                                    the previous call's class
        d.pop("_adm_local_sheds", None)    # per-call doomed-send count
        d.pop("stream", None)     # a previous call's stream must not
        #                           resurface on the new call's response
        hooks = d.get("_complete_hooks")
        if hooks:
            hooks.clear()
        if d.get("tried_servers") or d.get("_lb_fed") \
                or d.get("_lb_swept_n") is not None:
            with self._lb_lock:
                self.tried_servers.clear()
                self._lb_swept_n = None
                self._lb_fed = []

    def _set_issue_socket(self, sock) -> None:
        """Balanced per-socket in-flight accounting around every
        _issue_socket assignment (issue, retry/backup re-issue, reset,
        completion): socket.client_inflight counts calls issued and not
        yet completed on the socket, which gates the lazy-deadline
        pluck (join). The old->new swap runs under _arb_lock — a backup
        re-issue on the timer thread racing completion on the IO thread
        must not both read the same 'old' (double-decrement + leaked
        increment would skew the gate permanently); each thread then
        applies its own counter deltas, which commute."""
        d = self.__dict__
        with self._arb_lock:
            old = d.get("_issue_socket")
            if old is sock:
                return
            if sock is None:
                d.pop("_issue_socket", None)
            else:
                d["_issue_socket"] = sock
        lazy_to_arm = None
        if old is not None:
            with old.pending_lock:
                old.client_inflight -= 1
                old.inflight_calls.discard(self)
        if sock is not None:
            with sock.pending_lock:
                sock.client_inflight += 1
                sock.inflight_calls.add(self)
                if sock.client_inflight > 1:
                    # a lazy-deadline plucker owns this socket's input:
                    # OUR (possibly huge) response will run through its
                    # processing pass, during which its deadline cannot
                    # preempt — give it the real timer it skipped. The
                    # pending_lock orders this against the plucker's own
                    # register-or-arm decision in join(), so one side
                    # always arms.
                    lazy_to_arm = sock._lazy_plucker
            if sock.failed:
                # registration raced set_failed's drain: the drain may
                # have snapshotted before our add — re-trigger it (the
                # drain is idempotent) so this call can't sit out the
                # full deadline on a dead socket
                sock._drain_inflight_calls()
        if lazy_to_arm is not None and lazy_to_arm is not self:
            lazy_to_arm._arm_lazy_deadline()

    def _register_call(self) -> int:
        try:
            self.correlation_id = _pool().insert(self)
        except RuntimeError:
            # native pool exhausted (131072 live in-flight calls): fail
            # THIS call with a limit error instead of crashing the call
            # path — bounded-id backpressure, not unbounded growth
            raise OverflowError("correlation-id pool exhausted "
                                "(too many in-flight calls)") from None
        return self.correlation_id

    def _add_complete_hook(self, hook) -> None:
        """Completion-aware registration: a hook added AFTER the call
        completed (start_cancel can finish the call while _issue_rpc is
        still registering pooled-socket return hooks) runs immediately
        instead of silently never running — which would leak the pooled
        connection."""
        with self._arb_lock:
            if not self._completed:
                self._complete_hooks.append(hook)
                return
        try:
            hook(self)
        except Exception:
            pass

    def _complete(self) -> None:
        d = self.__dict__
        with self._arb_lock:
            self._completed = True
        self.end_us = time.monotonic_ns() // 1000
        # retry-budget accounting — here because _complete is the ONE
        # point every client completion flavor passes through: every
        # successful call slowly re-earns tokens, and a CLIENT-LOCAL
        # timeout (no responder: the deadline timer or the sync-pluck
        # joiner fired) drains one — a stalled cluster whose sockets
        # stay alive produces exactly these, and without the drain the
        # bucket would stay pinned at capacity while hedges pile load
        # onto the stall. Other failures drained in the channel's
        # failure paths already; a server-RESPONDED deadline shed is a
        # reject (responded_server set) and costs nothing.
        ch = d.get("_owner_channel")
        if ch is not None:
            rb = ch._retry_budget
            if rb is not None:
                if self.error_code == 0:
                    rb.refill()
                elif self.error_code == berr.ERPCTIMEDOUT \
                        and d.get("responded_server") is None:
                    rb.drain()
        # __dict__ peeks: lazily-created members that were never touched
        # need no completion work — don't materialize them just to find
        # them empty (this runs once per call)
        tids = d.get("_timer_ids")
        if tids:
            from brpc_tpu.fiber.timer import global_timer
            for tid in tids:
                global_timer().unschedule(tid)
            tids.clear()
        if self.failed():
            # a stream piggybacked on a failed call must not leak in the
            # global stream pool (timeout/socket-failure completion paths
            # never reach client_dispatch)
            stream = getattr(self, "stream", None)
            if stream is not None:
                stream.close()
        for hook in d.get("_complete_hooks", ()):
            try:
                hook(self)
            except Exception:
                pass
        # a completed call must not pin its socket (conn + portal read
        # blocks) for the controller's lifetime
        self._set_issue_socket(None)
        cb = self._done_cb
        # joiners may only observe completion AFTER end_us, timer
        # cancellation and the completion hooks above — _finalized (not
        # _completed, which arbitration publishes first) gates the
        # lazy-event fast path, and the done event is read under the
        # same lock join() creates it under, so a joiner either sees
        # _finalized or its fresh event is seen here
        with self._arb_lock:
            d["_finalized"] = True
            ev = d.get("_done_event")
        if ev is not None:
            ev.set()
        if cb is not None:
            cb(self)
        # after the done callback, so annotations recorded there land in
        # THIS call's line (the reference flushes at destruction, which
        # is also after done runs)
        self.flush_session_kv()

    def start_cancel(self) -> None:
        """Cancel an in-flight client call (Controller::StartCancel):
        completes NOW with ECANCELED; the late response finds no call
        and is dropped by the versioned-id arbitration. No-op if the
        call already finished or was never issued. Like the reference,
        cancellation is client-local — the server may still execute
        the handler."""
        if self.correlation_id == 0:
            # never registered (fresh/server-side/combo-parent
            # controller): taking id 0 would consume the reserved
            # slot-0 sentinel (see _call_pool setup)
            return
        with self._arb_lock:
            taken = take_call(self.correlation_id) is self
        if taken:
            self.set_failed(berr.ECANCELED, "canceled by caller")
            self._complete()

    # ------------------------------------------------- server-side cancel
    def is_canceled(self) -> bool:
        """Server side (Controller::IsCanceled): True once the client's
        connection is gone — a long handler should stop wasting work on
        a response nobody will read.

        Detection requires the connection's input fiber to be free to
        observe the EOF: run long handlers with
        ``ServerOptions(usercode_in_pthread=True)`` (the reference gets
        the same decoupling from its dedicated event-dispatcher
        bthreads). An in-place handler monopolizes the input fiber, so
        the EOF is only drained after it returns."""
        s = self._server_socket
        return bool(s is not None and s.failed)

    def notify_on_cancel(self, callback: Callable[[], None]) -> None:
        """Server side (Controller::NotifyOnCancel): run ``callback``
        when the client's connection dies; immediately if it already
        has. At most once per request — the subscription is dropped
        when the request completes, so keep-alive connections serving
        many requests don't accumulate stale notifications."""
        s = self._server_socket
        if s is None:
            return
        wrapped = lambda _sock: callback()   # noqa: E731
        self._cancel_subs.append((s, wrapped))
        s.on_failed(wrapped)

    def _drop_cancel_subs(self) -> None:
        """Called when the server request completes: a finished
        request must not hear about later connection deaths."""
        subs = self.__dict__.get("_cancel_subs")
        if not subs:
            return
        self._cancel_subs = []
        for s, cb in subs:
            try:
                s.off_failed(cb)
            except AttributeError:
                pass

    def _join_event(self):
        """Finalized -> None (nothing to wait for); else the lazily
        created done event, under the lock _complete reads it under.
        Gates on _finalized, not _completed: between the two, _complete
        is still cancelling timers and running completion hooks, and a
        joiner returning that early would read a stale end_us / race
        the LB feedback."""
        d = self.__dict__
        if d.get("_finalized"):
            return None
        with self._arb_lock:
            if d.get("_finalized"):
                return None
            return self._done_event   # lazy-created via _LAZY

    def join(self, timeout_s: Optional[float] = None) -> bool:
        """Block the calling thread until the call finishes.

        Non-worker threads first try the sync-pluck lane: the joiner
        adopts the issuing socket's input and processes its own
        response in place (Socket.pluck_until) — zero cross-thread
        wakes. Fiber workers and pluck-incapable transports fall to
        the event wait. A pre-send claim taken by the issue path
        (pluck_preclaim) is consumed here, or released on every path
        that cannot pluck — an unconsumed claim would wedge the
        socket (reads paused forever)."""
        pre = self.__dict__.pop("_pluck_preclaimed", None)
        try:
            if self._finalized:
                return True
            sock = self._issue_socket
            if pre is not None and pre is not sock:
                # a retry moved the call off the preclaimed socket:
                # release NOW — holding its lane (reads paused) while
                # we pluck the new socket would starve every other
                # call multiplexed on it for up to the deadline
                pre.pluck_release()
                pre = None
            pend = self.__dict__.get("_pending_deadline")
            if sock is not None and not sock.failed:
                from brpc_tpu.fiber.scheduler import current_group
                if current_group() is None:
                    deadline = time.monotonic() + (
                        timeout_s if timeout_s is not None else 86400.0)
                    if pend is not None:
                        # multiplex gate, bilateral with
                        # _set_issue_socket: under the same lock, either
                        # we see other calls in flight (keep the real
                        # timer), or we register as the socket's lazy
                        # plucker so a later issuer arms our timer for
                        # us — no window where a big foreign response
                        # can stall the deadline with no timer
                        with sock.pending_lock:
                            if sock.client_inflight > 1:
                                pend = None
                            else:
                                sock._lazy_plucker = self
                        if pend is None:
                            self._arm_lazy_deadline()
                    # lazy deadline (call_sync): the plucker IS the
                    # timer — clamp the pluck to the RPC deadline and
                    # fire the final timeout path ourselves if it passes
                    # (same thread-safe take the timer thread would do)
                    pluck_deadline = deadline if pend is None \
                        else min(deadline, pend[1])
                    # native receive loop (fastcore pluck_scan): armed
                    # by the small-frame issue path; completes through
                    # the same process_response_fast the turbo
                    # dispatcher uses
                    fast = None
                    pf = self.__dict__.get("_pluck_fast")
                    if pf is not None:
                        global _prf
                        if _prf is None:
                            from brpc_tpu.rpc.client_dispatch import \
                                process_response_fast as _prf_mod
                            _prf = _prf_mod
                        fast = (pf[0], self.correlation_id, pf[1], _prf)
                    try:
                        claimed = pre is sock
                        if claimed:
                            pre = None   # pluck_until settles the claim
                        if sock.pluck_until(lambda: self._finalized,
                                            pluck_deadline, fast=fast,
                                            preclaimed=claimed):
                            return True
                    except Exception:
                        pass   # pluck is an optimization, never a failure
                    finally:
                        if pend is not None:
                            with sock.pending_lock:
                                if sock._lazy_plucker is self:
                                    sock._lazy_plucker = None
                    if pend is not None and not self._finalized and \
                            time.monotonic() >= pend[1]:
                        try:
                            pend[0]._on_timeout(self)
                        except Exception:
                            pass
                        if self._finalized:
                            return True
                    if timeout_s is not None:
                        timeout_s = max(0.0, deadline - time.monotonic())
        finally:
            if pre is not None:
                try:
                    pre.pluck_release()
                except Exception:
                    pass
        # leaving the pluck lane (escalation, failed socket, fiber
        # caller, claim contention): the deadline needs a real timer
        self._arm_lazy_deadline()
        ev = self._join_event()
        return True if ev is None else ev.wait_pthread(timeout_s)

    def _arm_lazy_deadline(self) -> None:
        """Convert a pending (lazily-enforced) deadline into a real
        timer — called whenever the call leaves the sync-pluck lane, so
        deadline semantics are identical to the eager path from here."""
        pend = self.__dict__.pop("_pending_deadline", None)
        if pend is None or self._finalized:
            return
        ch, dl = pend
        from brpc_tpu.fiber.timer import global_timer
        tid = global_timer().schedule_at(dl, lambda: ch._on_timeout(self))
        self._timer_ids.append(tid)
        if self._completed:      # completion interleaved with the arm
            global_timer().unschedule(tid)

    async def join_async(self, timeout_s: Optional[float] = None) -> bool:
        self._arm_lazy_deadline()   # fiber joiner cannot pluck-enforce
        ev = self._join_event()
        return True if ev is None else await ev.wait(timeout_s)
