"""Controller: per-call context & state machine for both sides
(brpc/controller.{h,cpp}, SURVEY.md §2.6).

Client side owns: correlation id (a versioned slot in a global pool — the
bthread_id of the reference), deadline timer, retries, backup request,
response data. Completion is a one-shot event that both fibers (await) and
plain threads (block) can wait on, matching Join(cid)'s dual waiters.

Server side owns: error state, attachments, device payloads, the response
path handle.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, List, Optional

from brpc_tpu.butil.endpoint import EndPoint
from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.butil.resource_pool import ResourcePool
from brpc_tpu.fiber.sync import FiberEvent
from brpc_tpu.rpc import errno_codes as berr

# global correlation-id pool: id -> client Controller (the reference's
# bthread_id space, id.h:46)
_call_pool: ResourcePool = ResourcePool()
# reserve slot 0 forever: correlation id 0 must stay invalid, because
# proto3 serializes 0 as an absent field (a frame with no/zero
# correlation_id must never address a live call)
_call_pool.insert(None)


def address_call(correlation_id: int):
    return _call_pool.address(correlation_id)


def take_call(correlation_id: int):
    """Remove-and-return: the first finisher wins; stale responses and
    fired timers lose the race here (OnVersionedRPCReturned's version
    check, controller.cpp:575)."""
    return _call_pool.remove(correlation_id)


class Controller:
    def __init__(self):
        # ---- shared
        self.error_code: int = berr.OK
        self.error_text: str = ""
        self.log_id: int = 0
        self.request_attachment = IOBuf()
        self.response_attachment = IOBuf()
        self.request_device_arrays: List = []
        self.response_device_arrays: List = []
        self.remote_side: Optional[EndPoint] = None
        self.local_side: Optional[EndPoint] = None
        self.auth_token: str = ""
        self.auth_context = None   # server side: verified peer identity
        self.compress_type: int = 0
        self.trace_id: int = 0
        self.span_id: int = 0
        # ---- client side
        self.timeout_ms: Optional[float] = None
        self.max_retry: Optional[int] = None  # None = inherit channel option
        self.backup_request_ms: Optional[float] = None
        self.correlation_id: int = 0
        self.response_payload: Optional[IOBuf] = None
        self.response_msg: Any = None
        self._done_event = FiberEvent()
        self._done_cb: Optional[Callable[["Controller"], None]] = None
        self._timer_ids: List[int] = []
        self.current_try: int = 0
        self.start_us: int = 0
        self.end_us: int = 0
        self.used_backup: bool = False
        self.stream = None           # Stream piggybacked on this call
        # cluster bookkeeping: endpoints tried (for retry-elsewhere) and
        # completion hooks (LB feedback / circuit breaker / client spans)
        self.tried_servers: list = []
        self._complete_hooks: list = []
        # which server's response actually completed the call (set by
        # process_response; None on timeout/failure) — with backup
        # requests, tried_servers[-1] is NOT necessarily the winner
        self.responded_server = None
        # guards the tried/selection handshake between a late backup
        # attempt and the completion sweep (cluster_channel)
        self._lb_lock = threading.Lock()
        # serializes the take-and-complete / take-and-retry decisions
        # (the reference gets this from the bthread_id lock, id.h:46):
        # a response-error retry swaps the correlation id under this
        # lock, so the deadline timer can never interleave with the swap
        self._arb_lock = threading.RLock()
        self._lb_swept_n: Optional[int] = None
        self._lb_fed: list = []
        # ---- client call internals (set by Channel.call)
        self._service_name: str = ""
        self._method_name: str = ""
        self._request_bytes: bytes = b""
        # ---- server side
        self._server_socket = None
        self._response_sender: Optional[Callable] = None
        self._progressive = None    # ProgressiveAttachment (http chunked)
        self._session_local = None  # borrowed from the server's data pool
        self._session_kv: Optional[dict] = None   # kvmap.h SessionKV
        self._cancel_subs: list = []   # (socket, cb) notify_on_cancel subs
        self._completed = False    # set under _arb_lock by _complete

    def session_kv(self) -> dict:
        """Lazily-created per-call key/value annotations (kvmap.h +
        Controller::SessionKV): whatever the app records here is flushed
        to the log in one line when the call completes, so everything
        about one session lands greppable together. Flushing CLEARS the
        map, so on a reused controller any annotation added after the
        previous completion belongs to the NEXT call."""
        if self._session_kv is None:
            self._session_kv = {}
        return self._session_kv

    def flush_session_kv(self) -> None:
        """Log-and-clear (FlushSessionKV, controller.cpp:160: flushed at
        controller destruction; ours flushes at call completion). Never
        raises: a kv value whose __str__ explodes must not abort the
        completion path it runs on (join() would hang)."""
        kv = self._session_kv
        if not kv:
            return
        self._session_kv = None
        try:
            pairs = " ".join(f"{k}={v}" for k, v in kv.items())
            logging.getLogger("brpc_tpu.session").info(
                "Session ends. %s @%s.%s log_id=%d", pairs,
                self._service_name or "?", self._method_name or "?",
                self.log_id)
        except Exception:
            logging.getLogger("brpc_tpu.session").exception(
                "session_kv flush failed")

    def create_progressive_attachment(
            self, content_type: str = "application/octet-stream"):
        """HTTP chunked-response body fed after the handler returns
        (progressive_attachment.h); native streams use Stream instead."""
        from brpc_tpu.rpc.progressive import ProgressiveAttachment
        self._progressive = ProgressiveAttachment(content_type)
        return self._progressive

    def session_local_data(self):
        """Reusable per-request object from ServerOptions.
        session_local_data_factory (server.h session_local_data)."""
        return self._session_local

    # ---------------------------------------------------------------- names
    @property
    def service_name(self) -> str:
        return self._service_name

    @property
    def method_name(self) -> str:
        return self._method_name

    # --------------------------------------------------------------- error
    def failed(self) -> bool:
        return self.error_code != berr.OK

    def set_failed(self, code: int, text: str = "") -> None:
        self.error_code = code
        self.error_text = text or berr.errno_name(code)

    def reset_error(self) -> None:
        self.error_code = berr.OK
        self.error_text = ""

    def latency_us(self) -> int:
        return max(0, self.end_us - self.start_us)

    # ---------------------------------------------------- client completion
    def _reset_for_call(self) -> None:
        """Per-CALL client state must reset on controller reuse (called
        at the top of Channel.call): a stale one-shot done event would
        make join() return before the new response arrives (with the
        previous call's payload), stale tried/attempt bookkeeping would
        exclude healthy servers or trip the cluster channel's
        late-attempt guard, a stale retry counter would shrink the new
        retry budget, and stale completion hooks (pooled-connection
        returns) would re-run and double-insert sockets into the pool.
        LB bookkeeping resets under _lb_lock — a still-in-flight backup
        attempt from the PREVIOUS call must not interleave with the
        reset and leak its selection."""
        self._done_event = FiberEvent()
        self.reset_error()
        self.current_try = 0
        with self._arb_lock:
            self._completed = False
        self.end_us = 0
        self.response_payload = None
        self.response_attachment = IOBuf()
        self.response_device_arrays = []
        self.responded_server = None
        self.used_backup = False
        self.stream = None        # a previous call's stream must not
        #                           resurface on the new call's response
        self._complete_hooks.clear()
        with self._lb_lock:
            self.tried_servers.clear()
            self._lb_swept_n = None
            self._lb_fed = []

    def _register_call(self) -> int:
        self.correlation_id = _call_pool.insert(self)
        return self.correlation_id

    def _add_complete_hook(self, hook) -> None:
        """Completion-aware registration: a hook added AFTER the call
        completed (start_cancel can finish the call while _issue_rpc is
        still registering pooled-socket return hooks) runs immediately
        instead of silently never running — which would leak the pooled
        connection."""
        with self._arb_lock:
            if not self._completed:
                self._complete_hooks.append(hook)
                return
        try:
            hook(self)
        except Exception:
            pass

    def _complete(self) -> None:
        with self._arb_lock:
            self._completed = True
        self.end_us = time.monotonic_ns() // 1000
        from brpc_tpu.fiber.timer import global_timer
        for tid in self._timer_ids:
            global_timer().unschedule(tid)
        self._timer_ids.clear()
        if self.failed():
            # a stream piggybacked on a failed call must not leak in the
            # global stream pool (timeout/socket-failure completion paths
            # never reach client_dispatch)
            stream = getattr(self, "stream", None)
            if stream is not None:
                stream.close()
        for hook in self._complete_hooks:
            try:
                hook(self)
            except Exception:
                pass
        cb = self._done_cb
        self._done_event.set()
        if cb is not None:
            cb(self)
        # after the done callback, so annotations recorded there land in
        # THIS call's line (the reference flushes at destruction, which
        # is also after done runs)
        self.flush_session_kv()

    def start_cancel(self) -> None:
        """Cancel an in-flight client call (Controller::StartCancel):
        completes NOW with ECANCELED; the late response finds no call
        and is dropped by the versioned-id arbitration. No-op if the
        call already finished or was never issued. Like the reference,
        cancellation is client-local — the server may still execute
        the handler."""
        if self.correlation_id == 0:
            # never registered (fresh/server-side/combo-parent
            # controller): taking id 0 would consume the reserved
            # slot-0 sentinel (see _call_pool setup)
            return
        with self._arb_lock:
            taken = take_call(self.correlation_id) is self
        if taken:
            self.set_failed(berr.ECANCELED, "canceled by caller")
            self._complete()

    # ------------------------------------------------- server-side cancel
    def is_canceled(self) -> bool:
        """Server side (Controller::IsCanceled): True once the client's
        connection is gone — a long handler should stop wasting work on
        a response nobody will read.

        Detection requires the connection's input fiber to be free to
        observe the EOF: run long handlers with
        ``ServerOptions(usercode_in_pthread=True)`` (the reference gets
        the same decoupling from its dedicated event-dispatcher
        bthreads). An in-place handler monopolizes the input fiber, so
        the EOF is only drained after it returns."""
        s = self._server_socket
        return bool(s is not None and s.failed)

    def notify_on_cancel(self, callback: Callable[[], None]) -> None:
        """Server side (Controller::NotifyOnCancel): run ``callback``
        when the client's connection dies; immediately if it already
        has. At most once per request — the subscription is dropped
        when the request completes, so keep-alive connections serving
        many requests don't accumulate stale notifications."""
        s = self._server_socket
        if s is None:
            return
        wrapped = lambda _sock: callback()   # noqa: E731
        self._cancel_subs.append((s, wrapped))
        s.on_failed(wrapped)

    def _drop_cancel_subs(self) -> None:
        """Called when the server request completes: a finished
        request must not hear about later connection deaths."""
        subs, self._cancel_subs = self._cancel_subs, []
        for s, cb in subs:
            try:
                s.off_failed(cb)
            except AttributeError:
                pass

    def join(self, timeout_s: Optional[float] = None) -> bool:
        """Block the calling thread until the call finishes."""
        return self._done_event.wait_pthread(timeout_s)

    async def join_async(self, timeout_s: Optional[float] = None) -> bool:
        return await self._done_event.wait(timeout_s)
