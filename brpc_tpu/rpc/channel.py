"""Channel: the client stub (brpc/channel.{h,cpp}).

Owns protocol choice, timeout/retry/backup-request defaults, and the
connection to a single server (naming-service + load-balanced cluster
channels compose on top — see rpc/cluster_channel.py). The call path
mirrors Channel::CallMethod -> Controller::IssueRPC -> Socket::Write
(SURVEY.md §3.1): serialize, register correlation id, pack, enqueue,
arm deadline/backup timers, wait.
"""

from __future__ import annotations

import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional

from brpc_tpu.butil.endpoint import EndPoint, str2endpoint
from brpc_tpu.butil.flags import flag as _flag
from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.fiber import TaskControl, global_control
from brpc_tpu.fiber.timer import global_timer
from brpc_tpu.protocol.proto import tpu_rpc_meta_pb2 as pb
from brpc_tpu.protocol.tpu_std import (_HDR as _TPU_HDR, MAGIC as _TPU_MAGIC,
                                       SMALL_FRAME_MAX,
                                       _TAG_ATTACHMENT_SIZE,
                                       _TAG_CORRELATION_ID, _varint,
                                       pack_frame_head, pack_message,
                                       pack_small_frame, serialize_payload)

_TAG_CORRELATION_ID_B = _TAG_CORRELATION_ID.to_bytes(1, "big")
_TAG_ATTACHMENT_SIZE_B = _TAG_ATTACHMENT_SIZE.to_bytes(1, "big")
from brpc_tpu.bvar.reducer import Adder
from brpc_tpu.rpc import backend_stats as _bs
from brpc_tpu.rpc import errno_codes as berr
from brpc_tpu.rpc.controller import Controller, address_call, take_call
from brpc_tpu.transport import socket as _socket_mod
from brpc_tpu.transport.input_messenger import InputMessenger
from brpc_tpu.transport.socket import Socket, create_client_socket


def _fail_inflight_calls(sock, calls) -> None:
    """Socket-failure fan-out: every client call still issued on the
    dead socket fails (or retries elsewhere) NOW instead of sitting out
    its full deadline — the reference's SetFailed -> bthread_id_error
    behavior (socket.cpp; OnVersionedRPCReturned sees EFAILEDSOCKET
    immediately). Runs on a fiber (retries may reconnect, which blocks);
    take_call arbitration on the SNAPSHOT correlation id makes racing
    completions — and a controller recycled onto a brand-new call
    before this fiber ran — a no-op."""
    reason = str(sock.fail_reason or "socket failed")
    for cntl, cid, seq in calls:
        ch = getattr(cntl, "_owner_channel", None)
        try:
            if ch is not None:
                ch._maybe_retry(cntl, berr.EFAILEDSOCKET,
                                f"socket failed: {reason}",
                                failed_ep=sock.remote_endpoint,
                                expect_cid=cid, expect_seq=seq)
                continue
            with cntl._arb_lock:
                if cntl.__dict__.get("_issue_seq") != seq:
                    continue   # re-issued since the snapshot: stale
                taken = take_call(cid) is cntl
            if taken:
                cntl.set_failed(berr.EFAILEDSOCKET,
                                f"socket failed: {reason}")
                cntl._complete()
        except Exception:
            pass   # one broken call must not strand the rest


_socket_mod.inflight_failer = _fail_inflight_calls


_client_fdr = None   # lazily built; False = extension unavailable

# retries/backups not issued because the call's deadline budget could
# not possibly cover them (/vars) — the client half of deadline
# propagation: an attempt that cannot complete is never launched
nretry_suppressed = Adder().expose("retry_suppressed_budget")

# retries/hedges suppressed because the channel's retry token bucket
# ran dry (RetryBudget — overload must not be amplified) — /vars
nretry_throttled = Adder().expose("retry_throttled")

# hedges not armed because the remaining deadline budget sat under the
# fastest backend's recent p50 (a hedge that cannot win is pure load;
# Dean & Barroso, The Tail at Scale) — /vars
nhedge_suppressed = Adder().expose("hedge_suppressed_budget")

# sends failed fast CLIENT-side against a piggybacked admission
# threshold (DAGOR: doomed traffic stops at the source instead of
# burning a socket round trip to be shed at the server's door) — /vars
nclient_priority_shed = Adder().expose("client_priority_shed")

# admission-threshold cache discipline (Channel._adm_cache): entries
# expire after TTL, a broken CONNECTION drops its backend's entries at
# once (a restarted backend must not inherit a stale threshold — see
# _on_attempt_failed), and every PROBE interval one doomed send per
# (backend, service) goes through anyway so a relaxing threshold is
# observed
ADM_THRESHOLD_TTL_S = 5.0
ADM_PROBE_INTERVAL_S = 0.25

# failure codes that never drain the retry token bucket: overload
# REJECTS cost the server microseconds at the door (see _maybe_retry),
# and a naming-empty fail-fast burns nothing anywhere — draining on it
# would leave the channel throttled long after the naming url is fixed
# (NamingEmptyError's stated contract)
_NO_DRAIN_CODES = frozenset(_bs.REJECT_CODES) | {berr.ENAMINGEMPTY}

_csc = None   # lazily bound server_dispatch.current_serving_controller


def client_fast_drain_hook(options):
    """The client-side chunk fast lane for a channel's sockets (None
    when inapplicable): only default-protocol (tpu_std) channels — the
    lane scans MAGIC-framed responses."""
    if options.protocol not in ("", "tpu_std"):
        return None
    global _client_fdr
    if _client_fdr is None:
        from brpc_tpu.rpc.client_dispatch import make_client_fast_drain
        _client_fdr = make_client_fast_drain() or False
    return _client_fdr or None


@dataclass
class ChannelOptions:
    protocol: str = "tpu_std"
    connection_type: str = "single"      # single | pooled | short
    # stable channel name for per-backend client telemetry (/backends,
    # /lb_trace, the backend_stats prometheus labels); empty = an
    # auto-generated "channel-N" (cluster channels default to their
    # naming url). Reuse ONE name for channels that mean the same
    # dependency — cells are keyed by it.
    name: str = ""
    timeout_ms: Optional[float] = 1000.0
    max_retry: int = 3
    backup_request_ms: Optional[float] = None
    auth_token: str = ""
    # pluggable Authenticator (rpc/auth.py): generate_credential() result
    # rides the request meta; wins over auth_token
    auth: Optional[Any] = None
    # app-level health check (details/health_check.cpp:59-144): a
    # callable(EndPoint)->bool that must succeed before a dead server is
    # revived — use rpc_health_check(...) for the RPC-probe flavor.
    # Cluster channels only; None keeps the bare-connect gate.
    app_health_check: Optional[Any] = None
    # process-global connection sharing for connection_type="single"
    # (socket_map.h:147): channels to the same (endpoint, protocol) reuse
    # one Socket
    share_connections: bool = True
    # pluggable retry decision (retry_policy.h): RetryPolicy instance or
    # callable(Controller)->bool; None = default (transport/availability
    # errors retry, semantic errors don't). Consulted for every failed
    # attempt while tries remain — including server-returned errors.
    retry_policy: Optional[Any] = None
    # per-channel retry token bucket (retry_policy.RetryBudget — the
    # gRPC retryThrottling shape): failed attempts drain, successes
    # slowly refill, and an empty bucket suppresses retries AND hedges
    # (`retry_throttled` bvar) so a cluster brown-out cannot be
    # amplified into an outage by the retry storm. True = defaults
    # (100 tokens, 0.1 refill), an instance = custom sizing, None = off.
    retry_budget: Optional[Any] = None
    # how long ClusterChannel's constructor waits for the naming
    # service's first server-list update before giving up (calls then
    # fail fast with ENAMINGEMPTY + the `naming_empty` bvar while the
    # list stays empty)
    naming_wait_s: float = 5.0
    # naming-service filter (naming_service_filter.h): callable
    # (EndPoint)->bool; servers it rejects never reach the load
    # balancer. Cluster channels only.
    ns_filter: Optional[Any] = None
    # channel-group retry budget (ISSUE 14): every channel in a process
    # naming the same group shares ONE RetryBudget — a process holding
    # N channels to one cluster otherwise gives a brown-out N buckets
    # of retry fuel (the PR 10 amplification hole). The group's sizing
    # comes from the FIRST member's retry_budget spec; later members
    # join the existing bucket. Empty = per-channel budget semantics
    # unchanged.
    budget_group: str = ""




def connect_dedup(lock, read_fn, write_fn, make_fn):
    """Connect outside the lock, publish under it; exactly one winner per
    slot, losers are discarded (shared by Channel and ClusterChannel)."""
    cur = read_fn()
    if cur is not None and not cur.failed:
        return cur
    new = make_fn()
    with lock:
        cur = read_fn()
        if cur is not None and not cur.failed:
            loser = new
        else:
            write_fn(new)
            loser = None
    if loser is not None:
        loser.set_failed(ConnectionError("duplicate connect discarded"))
        with lock:
            cur = read_fn()
        if cur is None or cur.failed:
            raise ConnectionError("connection closed concurrently")
        return cur
    return new


_chan_seq = itertools.count(1)


class Channel:
    def __init__(self, address: Optional[str | EndPoint] = None,
                 options: Optional[ChannelOptions] = None,
                 control: Optional[TaskControl] = None):
        self.options = options or ChannelOptions()
        # per-backend telemetry identity (backend_stats cells + the LB
        # decision ring are keyed by it); subclasses override
        # _default_stats_name (ClusterChannel: its naming url) so the
        # registration happens exactly once
        self._stats_name = self.options.name or self._default_stats_name()
        _bs.global_stats().register_channel(self._stats_name, self)
        if self.options.budget_group:
            # cluster-scoped token bucket: all channels in the group
            # drain/refill ONE budget (retry_policy.shared_retry_budget)
            from brpc_tpu.rpc.retry_policy import shared_retry_budget
            self._retry_budget = shared_retry_budget(
                self.options.budget_group, self.options.retry_budget)
        elif self.options.retry_budget is not None:
            from brpc_tpu.rpc.retry_policy import RetryBudget
            self._retry_budget = RetryBudget.resolve(
                self.options.retry_budget)
        else:
            self._retry_budget = None
        # piggybacked admission thresholds, keyed (backend, service):
        # plain dict, atomic get/set/pop only — fed by the response
        # paths, consulted by the issue path's doomed-send fail-fast.
        # Empty (the overwhelming common case) costs one truthiness
        # check per issue/response.
        self._adm_cache: dict = {}
        self._adm_sweep = 0.0          # last stale-entry sweep stamp
        self._control = control or global_control()
        self._messenger = InputMessenger(control=self._control)
        self._socket: Optional[Socket] = None
        self._socket_lock = threading.Lock()
        self._map_key = None                 # global SocketMap lease key
        self._endpoint: Optional[EndPoint] = None
        self._framer_cache = None
        # (service, method, timeout_ms, auth_token) -> serialized RpcMeta
        # prefix (everything but correlation_id/attachment_size); the
        # small-call fast path appends those as hand-encoded varint
        # fields per call instead of building a pb object
        self._meta_prefix_cache: dict = {}
        # pooled-connection_type freelist (socket.h connection pooling)
        self._conn_pool: List[Socket] = []
        self._pool_lock = threading.Lock()
        self._pool_closed = False
        if address is not None:
            self.init(address)

    def init(self, address: str | EndPoint) -> None:
        self._endpoint = (address if isinstance(address, EndPoint)
                          else str2endpoint(address))

    def _default_stats_name(self) -> str:
        return f"channel-{next(_chan_seq)}"

    @property
    def stats_name(self) -> str:
        """This channel's row key on /backends and /lb_trace."""
        return self._stats_name

    lb_name = None    # /backends channel header; ClusterChannel overrides

    def _label_socket(self, s, ep) -> None:
        """Tag a channel-owned socket with its owner identity so the
        /connections client rows are attributable at a glance. First
        owner wins on a socket_map-shared connection — the label names
        who DIALED it, not every multiplexed tenant."""
        ud = s.user_data
        ud.setdefault("channel", self._stats_name)
        ud.setdefault("backend", _bs.ep_key(ep))

    # ---------------------------------------------------------- connection
    def _get_socket(self) -> Socket:
        def _make():
            s = create_client_socket(
                self._endpoint, on_input=self._messenger.on_new_messages,
                control=self._control)
            s.fast_drain = client_fast_drain_hook(self.options)
            self._label_socket(s, self._endpoint)
            return s

        if (self.options.connection_type == "single"
                and self.options.share_connections):
            # process-global sharing (socket_map.h:147): one multiplexed
            # connection per (endpoint, protocol) across ALL channels;
            # this channel holds one refcounted lease on it
            from brpc_tpu.transport.socket_map import (SocketMap,
                                                       global_socket_map)
            with self._socket_lock:
                s = self._socket
            # probe OUTSIDE _socket_lock: a dead peer turns the probe
            # into set_failed, whose on_failed callbacks run inline and
            # may re-enter the channel (callback-under-lock)
            if s is not None and not s.failed \
                    and not s.probe_unobserved():
                return s
            # the key carries the credential flavor (socket_map.h keys
            # include ssl/auth settings): channels with different
            # credentials must not multiplex one verified connection
            auth_part = (self.options.auth_token
                         or (f"auth#{id(self.options.auth)}"
                             if self.options.auth is not None else ""))
            key = SocketMap.key(self._endpoint,
                                f"{self.options.protocol}|{auth_part}")
            s = global_socket_map().acquire(key, _make)
            self._label_socket(s, self._endpoint)
            with self._socket_lock:
                old, self._socket = self._socket, s
                self._map_key = key
            if old is not None:
                # this channel holds exactly ONE lease: drop the stale
                # socket's lease — or, when a concurrent first call
                # already stored this very socket, the duplicate lease
                # the second acquire() just took
                global_socket_map().release(key, old)
            return s

        def _write(s):
            self._socket = s

        return connect_dedup(self._socket_lock, lambda: self._socket,
                             _write, _make)

    def device_lane_kind(self,
                         timeout_s: float = 2.0) -> Optional[str]:
        """The device-lane flavor of this channel's connection
        ('local-d2d' / 'pjrt-pull' / 'staged'), or None when the
        transport has no device lane at all. Dials lazily and waits
        (bounded) for the lane hello, since the flavor is negotiated —
        combo channels probe this once per generation before lowering
        a device fan-out to one XLA collective."""
        try:
            sock = self._get_socket()
        except Exception:
            return None
        conn = getattr(sock, "conn", None)
        if conn is None or not getattr(conn, "supports_device_lane", False):
            return None
        kind = getattr(conn, "lane_kind", None)
        if kind is None:
            return None
        if getattr(conn, "peer_info", True) is None:
            # hello still in flight: the kind would read as the staged
            # floor; wait for the negotiated answer
            deadline = time.monotonic() + timeout_s
            while conn.peer_info is None:
                if sock.failed or time.monotonic() >= deadline:
                    break
                time.sleep(0.001)
            kind = conn.lane_kind
        return kind

    def close(self) -> None:
        """Release the connection(s); the channel may be re-used (it will
        reconnect lazily)."""
        with self._socket_lock:
            s, self._socket = self._socket, None
            key, self._map_key = self._map_key, None
        if s is not None:
            if key is not None:
                # shared socket: return the lease; it closes only when
                # the last channel lets go
                from brpc_tpu.transport.socket_map import global_socket_map
                global_socket_map().release(key, s)
            elif not s.failed:
                s.set_failed(ConnectionError("channel closed"))
        with self._pool_lock:
            pool, self._conn_pool = self._conn_pool, []
            self._pool_closed = True
        for sock in pool:
            if not sock.failed:
                sock.set_failed(ConnectionError("channel closed"))

    # ---------------------------------------------------------------- call
    def call(self, service_name: str, method_name: str, request: Any = b"",
             cntl: Optional[Controller] = None,
             done: Optional[Callable[[Controller], None]] = None,
             request_device_arrays: Optional[List] = None,
             response_class=None, stream_options=None,
             _lazy_deadline: bool = False) -> Controller:
        """Begin an RPC; returns the Controller immediately. Wait with
        cntl.join() (thread) / await cntl.join_async() (fiber), or pass
        ``done`` for callback style — the async CallMethod triple."""
        cntl = cntl or Controller()
        if "_completed" in cntl.__dict__:
            cntl._reset_for_call()   # reused controller: full reset
        else:
            # fresh controller: nothing to reset — just arm completion
            # (the done event itself is lazy: created by the first
            # joiner that arrives before completion)
            cntl.__dict__["_completed"] = False
        cntl.start_us = time.monotonic_ns() // 1000
        if cntl.timeout_ms is None:
            cntl.timeout_ms = self.options.timeout_ms
        # deadline inheritance: a call made INSIDE a serving handler may
        # not outlive the request being served — shrink to the parent's
        # remaining budget (min rule; docs/robustness.md). A parent with
        # no deadline inherits nothing.
        global _csc
        if _csc is None:
            from brpc_tpu.rpc.server_dispatch import \
                current_serving_controller as _csc_mod
            _csc = _csc_mod
        parent = _csc()
        if parent is not None and parent is not cntl:
            # trace propagation: a nested call joins the serving
            # request's trace (the server span's id becomes this call's
            # parent), so a client->A->B chain assembles into ONE tree
            # across processes (tools/trace.py). Only when the parent
            # actually carries a trace — otherwise the fast framing
            # path stays trace-free.
            if parent.trace_id and not cntl.trace_id:
                cntl.trace_id = parent.trace_id
                cntl.span_id = parent.span_id
            # priority inheritance (ISSUE 14): a nested call carries
            # the serving request's business priority unless the
            # caller explicitly set one — a chain's class survives
            # hops exactly like its deadline budget does below (same
            # fiber-local path; 0 = unset inherits, a reused
            # controller was reset by _reset_for_call)
            if parent.request_priority and not cntl.request_priority:
                cntl.request_priority = parent.request_priority
            rem = parent.remaining_ms()
            if rem is not None:
                if rem <= 0.0:
                    # the parent's budget is already gone: issuing would
                    # waste a downstream server's time on a reply nobody
                    # can use — fail fast, before any socket work
                    cntl._done_cb = done
                    cntl.set_failed(berr.ERPCTIMEDOUT,
                                    "parent request's deadline budget "
                                    "exhausted before nested call")
                    cntl._complete()
                    return cntl
                if cntl.timeout_ms is None or cntl.timeout_ms > rem:
                    cntl.timeout_ms = rem
        if cntl.timeout_ms is not None:
            # the client-side absolute deadline: retry/backup scheduling
            # clamps to it (cheap: one subtraction per retry decision)
            cntl.__dict__["_deadline_ns"] = time.monotonic_ns() \
                + int(cntl.timeout_ms * 1e6)
        if cntl.max_retry is None:
            cntl.max_retry = self.options.max_retry
        if cntl.backup_request_ms is None:
            cntl.backup_request_ms = self.options.backup_request_ms
        cntl._done_cb = done
        if not cntl.auth_token:
            if self.options.auth is not None:
                cntl.auth_token = self.options.auth.generate_credential()
            else:
                cntl.auth_token = self.options.auth_token
        if request_device_arrays:
            cntl.request_device_arrays = list(request_device_arrays)
        if response_class is not None:
            cntl.response_msg = response_class()
        elif cntl.response_msg is not None:
            cntl.response_msg = None
        cntl._service_name = service_name
        cntl._method_name = method_name
        cntl._request_bytes = serialize_payload(request)
        if cntl.compress_type:
            # compress once here, not per (re)issue attempt
            from brpc_tpu.rpc.compress import compress
            cntl._request_bytes = compress(cntl._request_bytes,
                                           cntl.compress_type)
        if stream_options is not None:
            # stream setup piggybacks on this RPC (StreamCreate)
            from brpc_tpu.rpc.stream import Stream
            cntl.stream = Stream(stream_options)
        if _flag("rpcz_enabled"):
            from brpc_tpu.rpc.span import finish_span, start_client_span
            span = start_client_span(cntl, service_name, method_name)
            span.request_size = len(cntl._request_bytes)
            # the issue path stamps write_done_us on it (request write
            # completion) and the response path stamps first_byte /
            # parse_done — per-call, popped by _reset_for_call on reuse
            cntl.__dict__["_client_span"] = span
            # a reused Controller must not accumulate span hooks across
            # calls (stale spans would be re-finished with this call's
            # data and resubmitted)
            cntl._complete_hooks = [
                h for h in cntl._complete_hooks
                if not getattr(h, "_span_hook", False)]
            hook = lambda c, s=span: (finish_span(s, c),  # noqa: E731
                                      _settle_attempt_spans(c))
            hook._span_hook = True
            cntl._complete_hooks.append(hook)
        cntl._owner_channel = self  # response-path retry needs the channel
        try:
            cntl._register_call()
        except OverflowError as e:
            # bounded correlation-id space (native respool): complete
            # the call with ELIMIT instead of crashing the caller —
            # in-flight backpressure, matching concurrency-limiter
            # semantics
            cntl.set_failed(berr.ELIMIT, str(e))
            cntl._complete()
            return cntl
        if _lazy_deadline:
            # sync caller on a plain thread: the issue path may claim
            # the pluck lane BEFORE the send (pluck_preclaim), so the
            # response can only complete on the joining thread — on a
            # 1-core box the dispatcher otherwise wins the race to the
            # response about half the time (cross-thread completion +
            # event-wait join, the expensive shape). Set HERE, after
            # every path that could return without issuing — a leaked
            # flag would make a later done-callback call preclaim a
            # lane no joiner ever consumes (a wedged socket).
            from brpc_tpu.fiber.scheduler import current_group
            if current_group() is None:
                cntl.__dict__["_sync_fast"] = True
        self._issue_rpc(cntl)
        # deadline timer: final — no retry after it fires (HandleTimeout).
        # With inline input processing the response may have completed
        # DURING _issue_rpc: arming then would pin the controller in the
        # timer heap for the full timeout (the leak unschedule exists to
        # prevent), so check first — and re-check after arming, because a
        # completion on another thread can interleave with the arm.
        if cntl.timeout_ms is not None and not cntl._completed:
            if _lazy_deadline:
                # sync-pluck fast path (call_sync): the joiner that is
                # about to pluck enforces the deadline itself, so the
                # common completed-in-time call never touches the timer
                # heap (arm + cancel measured ~15-25us/call). join()
                # arms the real timer the moment the call leaves the
                # pluck lane (escalation, socket failure, fiber caller).
                cntl.__dict__["_pending_deadline"] = (
                    self, time.monotonic() + cntl.timeout_ms / 1e3)
            else:
                tid = global_timer().schedule_after(
                    cntl.timeout_ms / 1e3, lambda: self._on_timeout(cntl))
                cntl._timer_ids.append(tid)
                if cntl._completed:
                    global_timer().unschedule(tid)
        if cntl.backup_request_ms is not None and cntl.backup_request_ms > 0 \
                and not cntl._completed:
            tid = global_timer().schedule_after(
                cntl.backup_request_ms / 1e3, lambda: self._on_backup_timer(cntl))
            cntl._timer_ids.append(tid)
            if cntl._completed:
                global_timer().unschedule(tid)
        return cntl

    def call_sync(self, service_name: str, method_name: str, request: Any = b"",
                  cntl: Optional[Controller] = None, **kw) -> Controller:
        cntl = self.call(service_name, method_name, request, cntl=cntl,
                         _lazy_deadline=True, **kw)
        budget = None if cntl.timeout_ms is None else cntl.timeout_ms / 1e3 + 5.0
        cntl.join(budget)
        return cntl

    async def call_async(self, service_name: str, method_name: str,
                         request: Any = b"", cntl: Optional[Controller] = None,
                         **kw) -> Controller:
        cntl = self.call(service_name, method_name, request, cntl=cntl, **kw)
        budget = None if cntl.timeout_ms is None else cntl.timeout_ms / 1e3 + 5.0
        await cntl.join_async(budget)
        return cntl

    # ------------------------------------------------------------ internals
    def _framer(self):
        """Wire framing per ChannelOptions.protocol: tpu_std (default) or
        a frame-capable variant (hulu_pbrpc/sofa_pbrpc). Resolved once —
        the protocol is fixed for the channel's lifetime and this sits on
        the per-issue hot path."""
        framer = self._framer_cache
        if framer is not None:
            return framer
        if self.options.protocol in ("", "tpu_std"):
            framer = pack_message
        else:
            from brpc_tpu.protocol.registry import find_protocol
            proto = find_protocol(self.options.protocol)
            framer = getattr(proto, "frame", None)
            if framer is None:
                raise ValueError(
                    f"protocol {self.options.protocol!r} cannot frame "
                    f"Channel requests (use RedisClient/GrpcChannel/... "
                    f"for it)")
        self._framer_cache = framer
        return framer

    def _pick_socket(self, cntl: Controller) -> Socket:
        """Server/connection selection for one (re)issue; cluster channels
        override this with LB selection (controller.cpp:1048-1135).
        connection_type (socket.h GetPooledSocket/GetShortSocket):
          single — one multiplexed connection (default)
          pooled — exclusive connection per in-flight call, returned to
                   the pool on completion (protocols that can't
                   interleave, or parallelism past one conn's pipeline)
          short  — fresh connection per call, closed on completion"""
        ctype = self.options.connection_type
        if ctype in ("", "single"):
            return self._get_socket()
        if ctype == "pooled":
            sock = None
            while sock is None:
                with self._pool_lock:
                    self._pool_closed = False   # channel in use again
                    cand = self._conn_pool.pop() if self._conn_pool \
                        else None
                if cand is None:
                    break
                # probe OUTSIDE _pool_lock: a dead peer turns the probe
                # into set_failed, whose on_failed callbacks run inline
                # and may re-enter the channel (callback-under-lock)
                if not cand.failed and not cand.probe_unobserved():
                    sock = cand
            if sock is None:
                sock = create_client_socket(
                    self._endpoint, on_input=self._messenger.on_new_messages,
                    control=self._control)
                sock.fast_drain = client_fast_drain_hook(self.options)
                self._label_socket(sock, self._endpoint)

            def _return(c, s=sock):
                if s.failed:
                    return
                with self._pool_lock:
                    if not self._pool_closed:
                        self._conn_pool.append(s)
                        return
                # a call completing after close() must not re-populate the
                # emptied pool — nothing would ever close that socket again
                s.set_failed(ConnectionError("channel closed"))

            cntl._add_complete_hook(_return)
            return sock
        if ctype == "short":
            sock = create_client_socket(
                self._endpoint, on_input=self._messenger.on_new_messages,
                control=self._control)
            sock.fast_drain = client_fast_drain_hook(self.options)
            self._label_socket(sock, self._endpoint)
            cntl._add_complete_hook(
                lambda c, s=sock: s.failed or s.set_failed(
                    ConnectionError("short connection done")))
            return sock
        raise ValueError(f"unknown connection_type {ctype!r}")

    def _issue_rpc(self, cntl: Controller) -> None:
        """Pick socket, pack, enqueue (Controller::IssueRPC,
        controller.cpp:1010)."""
        # a retry may take a different framing branch than the first
        # attempt: the native-pluck hint is per-issue state, and the
        # new attempt gets a fresh failure-verdict latch. _issue_seq
        # names THIS attempt — failure paths capture it so a verdict
        # arriving after a re-issue (stale write callback, inflight
        # failer fiber that lost the race) is recognizably stale and
        # no-ops instead of judging the live attempt (the correlation
        # id alone cannot tell attempts apart: transport retries keep
        # it).
        d = cntl.__dict__
        d["_issue_seq"] = d.get("_issue_seq", 0) + 1
        d.pop("_pluck_fast", None)
        d.pop("_fail_handled", None)
        # a previous attempt's unconsumed pre-claim must not wedge its
        # socket (reads paused, claim never handed to a plucker); the
        # sync-fast hint is first-issue-only — a retry's joiner may
        # already be plucking another socket
        pre = d.pop("_pluck_preclaimed", None)
        if pre is not None:
            pre.pluck_release()
        sync_fast = d.pop("_sync_fast", False)
        try:
            sock = self._pick_socket(cntl)
        except (ConnectionError, OSError, ValueError) as e:
            # a selection failure with its own errno (naming-empty)
            # fails fast under that code; plain connect/pick failures
            # stay EFAILEDSOCKET (retry-elsewhere)
            self._maybe_retry(cntl, getattr(e, "berrno",
                                            berr.EFAILEDSOCKET), str(e))
            return
        if self._adm_cache and self._doomed_by_threshold(cntl, sock):
            # the chosen backend's piggybacked admission threshold
            # sits above this call's level: the send is DOOMED at THIS
            # backend — fail the attempt here, before the attempt
            # record, the span and the socket write (DAGOR: overload
            # stops burning sockets at the source), and hand it to the
            # retry machinery like the server's own shed would arrive:
            # a cluster pick already sits on tried_servers, so the
            # retry goes ELSEWHERE (one stalled node must not doom a
            # call the healthy survivor would serve), while a cluster
            # whose every backend is doomed fails in microseconds once
            # the pick exclusions exhaust. EPRIORITYSHED is a reject —
            # no token drain, no LALB penalty, no breaker darkening —
            # and probe-through keeps one send per interval flowing so
            # a relaxing threshold is observed.
            nclient_priority_shed.add(1)
            # a local shed never left the building: it is a re-pick,
            # not load on the cluster — wire-attempt accounting
            # (outage amplification) subtracts these
            d["_adm_local_sheds"] = d.get("_adm_local_sheds", 0) + 1
            self._maybe_retry(cntl, berr.EPRIORITYSHED,
                              "below piggybacked admission threshold "
                              f"at {sock.remote_endpoint} (shed "
                              "client-side)",
                              failed_ep=sock.remote_endpoint)
            return
        cntl.remote_side = sock.remote_endpoint
        cntl.local_side = sock.local_endpoint
        cntl._set_issue_socket(sock)  # sync-pluck lane (Controller.join)
        att = cntl.__dict__.get("request_attachment")
        # per-backend telemetry: this attempt is now issued AT a
        # concrete backend — open its stat-cell record (closed by
        # _on_attempt_failed or the completion sweep) and, under rpcz,
        # a per-attempt child span so retry/backup fan-out is visible
        # in the trace tree (submitted only for multi-attempt calls)
        if _bs.enabled():
            self._bs_attempt_begin(cntl, sock, att)
        span = d.get("_client_span")
        if span is not None:
            self._add_attempt_span(cntl, span, sock, d["_issue_seq"])
        # small-call fast path: the default protocol with none of the
        # optional sections (compress/trace/stream/device arrays) frames
        # from a cached meta prefix into ONE bytes object and sends it
        # straight from this context — no pb object, no IOBuf
        if (self._framer_cache is pack_message or
                (self._framer_cache is None
                 and self.options.protocol in ("", "tpu_std"))) \
                and not cntl.compress_type and not cntl.trace_id \
                and cntl.stream is None \
                and not cntl.__dict__.get("request_device_arrays") \
                and cntl.log_id == 0:
            key = (cntl._service_name, cntl._method_name, cntl.timeout_ms,
                   cntl.auth_token, cntl.request_priority)
            prefix = self._meta_prefix_cache.get(key)
            if prefix is None:
                m = pb.RpcMeta()
                m.request.service_name = cntl._service_name
                m.request.method_name = cntl._method_name
                if cntl.timeout_ms is not None:
                    m.request.timeout_ms = int(cntl.timeout_ms)
                if cntl.auth_token:
                    m.request.auth_token = cntl.auth_token
                if cntl.request_priority:
                    # part of the CONSTANT request submessage, so it
                    # rides the cached prefix (key carries it above)
                    m.request.priority = cntl.request_priority
                prefix = m.SerializeToString()
                if len(self._meta_prefix_cache) < 4096:
                    self._meta_prefix_cache[key] = prefix
            att_size = att.size if att else 0
            if len(cntl._request_bytes) + att_size <= SMALL_FRAME_MAX:
                # one-allocation C pack, single bytes frame
                wire = pack_small_frame(prefix, cntl.correlation_id,
                                        cntl._request_bytes,
                                        att.to_bytes() if att else b"")
                # a sync joiner may run the native pluck loop for this
                # call (Socket.pluck_until fast lane): the expected
                # response is a small tpu_std frame
                cntl.__dict__["_pluck_fast"] = (_TPU_MAGIC, SMALL_FRAME_MAX)
                # first-issue sync call: claim the lane pre-send so the
                # dispatcher can never win the race to the response
                if sync_fast and sock.pluck_preclaim():
                    d["_pluck_preclaimed"] = sock
            else:
                # large attachment: same cached-prefix meta (no pb build
                # per call), header+meta in one native allocation
                # (pack_frame_head — no Python varint joins), attachment
                # rides as zero-copy refs behind it
                head = pack_frame_head(prefix, cntl.correlation_id,
                                       att_size, len(cntl._request_bytes))
                wire = IOBuf()
                if cntl._request_bytes:
                    wire.append(head + cntl._request_bytes)
                else:
                    wire.append(head)
                if att_size:
                    wire.append_buf(att)
            try:
                sock.write(wire, on_done=lambda err, s=sock,
                           q=d["_issue_seq"], sp=d.get("_client_span"):
                           self._on_write_done(cntl, err, s, q, sp))
            except (BlockingIOError, ConnectionError, OSError) as e:
                self._maybe_retry(cntl, berr.EFAILEDSOCKET, str(e),
                                  failed_ep=sock.remote_endpoint)
            return
        meta = pb.RpcMeta()
        meta.request.service_name = cntl._service_name
        meta.request.method_name = cntl._method_name
        meta.request.log_id = cntl.log_id
        if cntl.timeout_ms is not None:
            meta.request.timeout_ms = int(cntl.timeout_ms)
        if cntl.auth_token:
            meta.request.auth_token = cntl.auth_token
        if cntl.request_priority:
            meta.request.priority = cntl.request_priority
        meta.correlation_id = cntl.correlation_id
        meta.compress_type = cntl.compress_type
        request_bytes = cntl._request_bytes  # already compressed in call()
        if cntl.trace_id:
            meta.trace_id = cntl.trace_id
            meta.span_id = cntl.span_id
        stream = getattr(cntl, "stream", None)
        if stream is not None:
            meta.stream_settings.stream_id = stream.id
            # plain assignment, NOT bind_socket: the stream is not
            # established yet — subscribing to this attempt's failure
            # would let a failed first attempt permanently close a
            # stream whose retried setup succeeds (failure semantics
            # attach in client_dispatch once the response arrives)
            stream.socket = sock
        use_lane = (bool(cntl.request_device_arrays)
                    and sock.conn.supports_device_lane)
        wire, lane = self._framer()(
            meta, request_bytes, attachment=_copy_buf(cntl.request_attachment),
            device_arrays=cntl.request_device_arrays, device_lane=use_lane)
        try:
            if lane is not None:
                # lane + wire must hit the conn as an adjacent pair:
                # another device-payload call slipping between them would
                # cross-match lane batches on the receiver. The defer-
                # flush hold moves the TCP syscalls for both frames out
                # from under lane_lock (one gather-write at release), so
                # concurrent callers serialize only on the queue pushes.
                conn = getattr(sock, "conn", None)
                hold = getattr(conn, "hold_flush", None)
                if hold is not None:
                    hold()
                try:
                    with sock.lane_lock:
                        # the device batch's stage tracker hangs its child
                        # span off this call's client span (trace inherit)
                        sock.write_device_payload(lane,
                                                  span=d.get("_client_span"))
                        # graftlint: disable=callback-under-lock -- lane_lock
                        # exists to make exactly this pair atomic (device
                        # batch + envelope adjacent on the conn); Socket.write
                        # only queues — it never parks and the on_done fires
                        # from the drain, not here
                        sock.write(wire, on_done=lambda err, s=sock,
                                   q=d["_issue_seq"],
                                   sp=d.get("_client_span"):
                                   self._on_write_done(cntl, err, s, q, sp))
                finally:
                    if hold is not None:
                        conn.release_flush()
            else:
                sock.write(wire, on_done=lambda err, s=sock,
                           q=d["_issue_seq"], sp=d.get("_client_span"):
                           self._on_write_done(cntl, err, s, q, sp))
        except (BlockingIOError, ConnectionError, OSError) as e:
            # lane backpressure / dead conn must fail the controller (or
            # retry), never escape to the caller with the call leaked
            self._maybe_retry(cntl, berr.EFAILEDSOCKET, str(e),
                              failed_ep=sock.remote_endpoint)

    def _on_write_done(self, cntl: Controller, err: Optional[BaseException],
                       sock=None, seq: Optional[int] = None, span=None):
        if err is None:
            # stage stamp: request write completed. ``span`` was
            # captured at issue time — a parked write completing after
            # the controller was recycled onto a NEW call must stamp
            # the OLD call's span, not the new one's. First attempt
            # wins (a retry's re-send must not overwrite the issue
            # timeline); a write parked behind a blocked conn (chaos
            # delay, full kernel buffer) lands here late and shows as
            # queue_us.
            if span is not None and not span.write_done_us:
                span.write_done_us = time.monotonic_ns() // 1000
            return
        self._maybe_retry(cntl, berr.EFAILEDSOCKET, str(err),
                          failed_ep=sock.remote_endpoint
                          if sock is not None else None,
                          expect_seq=seq)

    def _retry_policy(self):
        # resolved once: the policy is fixed at channel construction and
        # this sits on the per-failure hot path
        cached = getattr(self, "_retry_policy_cached", None)
        if cached is None:
            from brpc_tpu.rpc.retry_policy import resolve
            cached = self._retry_policy_cached = resolve(
                self.options.retry_policy)
        return cached

    def _maybe_retry(self, cntl: Controller, code: int, text: str,
                     failed_ep=None, expect_cid: Optional[int] = None,
                     expect_seq: Optional[int] = None) -> None:
        """Retry on transport failures while the call is still live
        (OnVersionedRPCReturned's error branch, controller.cpp:634);
        the retry policy decides whether this error class retries.

        One verdict per attempt: a failing socket can surface through
        TWO paths for the same call (the write's on_done error callback
        and set_failed's inflight fan-out) — the _fail_handled latch,
        check-and-set under the arbitration lock, lets exactly one of
        them act (a double verdict would re-issue the same correlation
        id twice or burn the retry budget and spuriously fail a live
        retry). ``expect_cid`` pins the CALL being judged (a recycled
        controller's new call must not be judged by a stale snapshot);
        ``expect_seq`` pins the ATTEMPT — transport retries keep the
        correlation id, so only the issue sequence can tell a verdict
        for a dead attempt from one against its live successor."""
        cid = cntl.correlation_id if expect_cid is None else expect_cid
        if self._adm_cache and code in (berr.EFAILEDSOCKET, berr.ECLOSE):
            # the CONNECTION to this backend died: whatever admission
            # threshold it piggybacked describes a process that may no
            # longer exist — a respawned backend must be approached
            # fresh, not doomed-shed against its predecessor's number
            # for up to a TTL (the fabric storm's recover tail pins
            # this). Before the latch on purpose: even a stale verdict
            # for an already re-issued attempt reports a real
            # connection death, and the drop is idempotent.
            ep = failed_ep or self._endpoint
            if ep is not None:
                epk = _bs.ep_key(ep)
                for key in [k for k in list(self._adm_cache)
                            if k[0] == epk]:
                    self._adm_cache.pop(key, None)
        if address_call(cid) is not cntl:
            return  # already completed (response/timeout won) or recycled
        # policy consult BEFORE the lock: user policy code must not run
        # while the timer thread can block on cntl._arb_lock
        allow = (cntl.current_try < cntl.max_retry
                 and self._policy_allows(cntl, code, text))
        if allow and self._budget_exhausted(cntl):
            # deadline clamp: a retry that cannot possibly complete
            # inside the remaining budget is not issued — the deadline
            # timer delivers the final verdict; this attempt's error
            # stands if it wins the take below
            allow = False
            nretry_suppressed.add(1)
        rb = self._retry_budget
        if allow and rb is not None and rb.throttled():
            # empty token bucket: the cluster is browning out and this
            # channel's retries would amplify it — the attempt's error
            # stands (gRPC retryThrottling / Tail-at-Scale discipline)
            allow = False
            nretry_throttled.add(1)
        with cntl._arb_lock:
            if address_call(cid) is not cntl:
                return
            if expect_seq is not None and \
                    cntl.__dict__.get("_issue_seq") != expect_seq:
                return  # stale verdict: the call was already re-issued
            if cntl.__dict__.get("_fail_handled"):
                return  # another failure path already judged this attempt
            cntl.__dict__["_fail_handled"] = True
            taken = False
            if allow:
                cntl.current_try += 1
            else:
                taken = take_call(cid) is cntl
        if rb is not None and code not in _NO_DRAIN_CODES:
            # drain AFTER the latch: the same dead socket surfaces
            # through two failure paths, and only the one that won the
            # latch may spend a token (a double drain per failure would
            # halve the budget's real capacity). Overload REJECTS never
            # drain: a shed costs the server microseconds at the door
            # (DAGOR: shed early, shed cheaply) and the shedding node
            # is already protecting itself — spending retry tokens on
            # them would throttle the retries-elsewhere that keep
            # goodput flat while one node sheds. The bucket guards
            # against EXPENSIVE failures: dead conns, timeouts.
            rb.drain()
        if allow:
            # report the failed attempt before moving on (the final
            # attempt is reported by the completion hook instead)
            self._on_attempt_failed(cntl, code, text, failed_ep)
            self._launch_retry(cntl, code, text)
            return
        if taken:
            cntl.set_failed(code, text)
            cntl._complete()

    def _budget_exhausted(self, cntl: Controller) -> bool:
        dl = cntl.__dict__.get("_deadline_ns")
        return dl is not None and time.monotonic_ns() >= dl

    def _launch_retry(self, cntl: Controller, code: int, text: str) -> None:
        """Issue the next attempt — immediately (the default,
        backoff-free policy) or after the policy's exponential backoff,
        clamped so the wait cannot outlive the deadline budget. The
        delayed re-issue re-checks call liveness: a deadline completion
        during the backoff wins and the retry evaporates."""
        backoff_s = 0.0
        try:
            # current_try was already incremented for the NEW attempt:
            # the policy contract wants the 0-based index of the attempt
            # that just FAILED
            view = _PolicyView(cntl, code, text,
                               current_try=max(0, cntl.current_try - 1))
            backoff_s = float(
                self._retry_policy().retry_backoff_s(view) or 0.0)
        except Exception:
            backoff_s = 0.0   # a broken policy must not kill the retry
        if backoff_s > 0.0:
            dl = cntl.__dict__.get("_deadline_ns")
            if dl is not None:
                backoff_s = min(backoff_s, max(
                    0.0, (dl - time.monotonic_ns()) / 1e9 - 1e-3))
        if backoff_s <= 0.0 and not cntl.__dict__.get("_retry_reentry"):
            self._reissue_guarded(cntl)
            return
        # deferred re-issue — two reasons share it: a backoff wait, or
        # a synchronously-failing endpoint (dead connect) that would
        # otherwise recurse issue->fail->retry->issue on this stack
        # until it overflows. The timer callback only SPAWNS: _issue_rpc
        # can block in connect() for seconds, and the process-wide timer
        # thread must keep firing deadlines/backups for every other call
        # (the chaos lane's no-hangs invariant depends on it).
        cid = cntl.correlation_id

        def _fire():
            if address_call(cid) is cntl:
                self._control.spawn(
                    (lambda: address_call(cid) is cntl
                     and self._reissue_guarded(cntl)),
                    name="retry_reissue")

        global_timer().schedule_after(max(0.0, backoff_s), _fire)

    def _reissue_guarded(self, cntl: Controller) -> None:
        """_issue_rpc with the reentry latch held: a failure inside it
        that retries again is recognized by _launch_retry and deferred
        to the timer instead of growing the stack."""
        d = cntl.__dict__
        d["_retry_reentry"] = True
        try:
            self._issue_rpc(cntl)
        finally:
            d.pop("_retry_reentry", None)

    def _policy_allows(self, cntl: Controller, code: int, text: str) -> bool:
        """Consult the retry policy with the failure visible through a
        READ-ONLY view (retry_policy.h's DoRetry contract takes a const
        Controller*): the real controller is never mutated, so this can
        run without cntl._arb_lock — mutating error_code in place here
        raced a concurrent timeout completion and could restore a
        completed call's error state to OK (a silent false success)."""
        view = _PolicyView(cntl, code, text)
        try:
            return bool(self._retry_policy().do_retry(view))
        except Exception:
            return False  # a broken policy must not loop retries

    def _retry_taken_call(self, cntl: Controller, code: int, text: str,
                          failed_ep=None, allow: Optional[bool] = None) -> bool:
        """Server-returned error on a call the caller has already WON
        via take_call: if policy + budget allow, re-register the
        controller under a FRESH correlation id (the analog of the
        reference's versioned-id bump — stale responses to the old id
        simply find no call) and re-issue. Returns True when the retry
        was launched; False means the caller completes the controller.

        Must be called with cntl._arb_lock held by the caller along
        with its take_call, so the deadline timer can't interleave: a
        timer firing during the id swap blocks on the lock, then finds
        the NEW id and completes the call with ERPCTIMEDOUT. Pass the
        policy verdict via ``allow`` (computed BEFORE the lock) so user
        policy code never runs on the timer thread's critical path."""
        rb = self._retry_budget
        if rb is not None and not _bs.is_reject(code, True):
            # a server-returned error IS a failed attempt — except the
            # reject class, which is a µs-cheap shed (see _maybe_retry).
            # This path only runs for RESPONDED errors, so ERPCTIMEDOUT
            # here is the server's own deadline shed: a reject too.
            rb.drain()
        if allow is None:
            allow = self._policy_allows(cntl, code, text)
        if cntl.current_try >= cntl.max_retry or not allow:
            return False
        if self._budget_exhausted(cntl):
            # same clamp as _maybe_retry: no budget, no new attempt
            nretry_suppressed.add(1)
            return False
        if rb is not None and rb.throttled():
            nretry_throttled.add(1)
            return False
        cntl.current_try += 1
        self._on_attempt_failed(cntl, code, text, failed_ep)
        cntl._register_call()
        return True

    # --------------------------------------- admission-threshold cache
    def _track_admission_threshold(self, ep, service: str,
                                   threshold: int) -> None:
        """Response-path hook: a server piggybacked its current DAGOR
        admission threshold (or, threshold 0, stopped — absent field /
        fast-lane response), cache it per (backend, service). Called
        only while a threshold rides the wire or the cache is non-empty
        — the calm hot path never lands here."""
        key = (_bs.ep_key(ep), service)
        now = time.monotonic()
        if threshold:
            ent = self._adm_cache.get(key)
            if ent is None:
                self._adm_cache[key] = [threshold, now, now]
            else:
                ent[0] = threshold
                ent[1] = now
        else:
            self._adm_cache.pop(key, None)
        if now - self._adm_sweep > ADM_THRESHOLD_TTL_S:
            # lazy sweep (at most once per TTL): an entry for a
            # (backend, service) the app stopped calling would
            # otherwise keep the cache truthy forever — every
            # issue/response of the whole channel paying the admission
            # lookups for a pair nobody uses
            self._adm_sweep = now
            for k in [k for k, e in list(self._adm_cache.items())
                      if now - e[1] > ADM_THRESHOLD_TTL_S]:
                self._adm_cache.pop(k, None)

    def _client_user_slot(self, cntl: Controller, sock) -> int:
        """This call's user sub-priority as the SERVER will compute it:
        the auth cookie when one rides the request, else the hash of
        the connection's client address — our socket's local endpoint
        IS the server's remote_endpoint, and the shared
        admission.cached_socket_slot keeps both sides' hash in
        lockstep."""
        from brpc_tpu.rpc.admission import cached_socket_slot, user_slot
        if cntl.auth_token:
            return user_slot(cntl.auth_token)
        return cached_socket_slot(sock, sock.local_endpoint)

    def _doomed_by_threshold(self, cntl: Controller, sock) -> bool:
        """True = this send's admission level sits below the backend's
        cached threshold and the probe window hasn't come around: fail
        it locally. Stale entries (TTL) expire here so a restarted or
        recovered backend is re-probed by the first send."""
        key = (_bs.ep_key(sock.remote_endpoint), cntl._service_name)
        ent = self._adm_cache.get(key)
        if ent is None:
            return False
        now = time.monotonic()
        if now - ent[1] > ADM_THRESHOLD_TTL_S:
            self._adm_cache.pop(key, None)
            return False
        from brpc_tpu.rpc.admission import compose_level
        level = compose_level(cntl.request_priority,
                              self._client_user_slot(cntl, sock))
        if level >= ent[0]:
            return False
        if now - ent[2] >= ADM_PROBE_INTERVAL_S:
            # probe-through: one doomed send per interval goes to the
            # wire anyway, so a relaxing threshold reaches this cache
            # (its response either carries a lower threshold or, calm,
            # clears the entry)
            ent[2] = now
            return False
        return True

    # ------------------------------------------- per-backend telemetry
    def _bs_cell(self, ep) -> tuple:
        """(backend_key, cell) for an endpoint, cached per channel —
        the hot path must not pay a registry lookup per attempt."""
        cells = self.__dict__.get("_bs_cells")
        if cells is None:
            cells = {}
            self.__dict__["_bs_cells"] = cells
        entry = cells.get(ep)
        if entry is None:
            key = _bs.ep_key(ep)
            entry = (key, _bs.global_stats().cell(self._stats_name, key))
            cells[ep] = entry
        return entry

    def _bs_attempt_begin(self, cntl: Controller, sock, att) -> None:
        key, cell = self._bs_cell(sock.remote_endpoint)
        cell.on_start(len(cntl._request_bytes) + (att.size if att else 0))
        _bs.attempt_start(cntl, [key, time.monotonic_ns(), cell],
                          self._bs_on_complete)

    def _bs_on_complete(self, cntl: Controller) -> None:
        _bs.call_complete(cntl)

    def _add_attempt_span(self, cntl: Controller, parent, sock,
                          seq: int) -> None:
        from brpc_tpu.rpc.span import start_attempt_span
        sp = start_attempt_span(parent, cntl._service_name,
                                cntl._method_name, seq,
                                self._bs_cell(sock.remote_endpoint)[0],
                                backup=cntl.used_backup)
        if cntl.used_backup:
            dec = cntl.__dict__.get("_hedge_decision")
            if dec is not None:
                # greppable arming evidence: remaining deadline budget
                # vs the p50 bar at decision time (the fabric storm's
                # "no hedge past budget" assert reads these)
                r, p = dec
                sp.annotate(
                    "hedge_armed remaining_ms=%s p50_ms=%s"
                    % ("inf" if r is None else round(r, 2),
                       "na" if p is None else round(p, 2)))
        with cntl._arb_lock:
            cntl.__dict__.setdefault("_attempt_spans", []).append(sp)

    def _close_attempt_span(self, cntl: Controller, code: int,
                            key: Optional[str] = None) -> None:
        """Stamp the failing attempt's span with its verdict — matched
        by backend key when the failure path knows it (with a
        concurrent backup, the newest open span belongs to a DIFFERENT,
        healthy backend and must not inherit this error); newest-open
        is the fallback when the endpoint is unknown."""
        spans = cntl.__dict__.get("_attempt_spans")
        if not spans:
            return
        now = time.monotonic_ns() // 1000
        with cntl._arb_lock:
            victim = None
            for sp in reversed(spans):
                if sp.end_us:
                    continue
                if victim is None:
                    victim = sp
                if key is not None and sp.remote_side == key:
                    victim = sp
                    break
            if victim is not None:
                victim.end_us = now
                victim.error_code = code

    def _on_attempt_failed(self, cntl: Controller, code: int, text: str,
                           failed_ep=None) -> None:
        """Per-attempt failure hook (LB feedback + circuit breaker ride
        the ClusterChannel override; per-backend stat cells and attempt
        spans settle here for every channel flavor). ``failed_ep``
        names the attempt's endpoint when the failure path knows it —
        with a concurrent backup selection, tried_servers[-1] may
        already be a DIFFERENT server."""
        ep = failed_ep or self._endpoint
        if _bs.enabled():
            _bs.attempt_error(self._stats_name, cntl, code, ep)
        self._close_attempt_span(cntl, code,
                                 _bs.ep_key(ep) if ep is not None else None)

    def _on_timeout(self, cntl: Controller) -> None:
        # under the arbitration lock: a response-error retry swapping
        # the correlation id must not interleave with this take — the
        # timer blocked here resumes against the NEW id and still ends
        # the call (the deadline is final across retries)
        with cntl._arb_lock:
            taken = take_call(cntl.correlation_id) is cntl
        if taken:
            cntl.set_failed(berr.ERPCTIMEDOUT,
                            f"deadline {cntl.timeout_ms}ms exceeded")
            cntl._complete()

    def _hedge_p50_ms(self) -> Optional[float]:
        """The fastest backend's recent p50 (ms) among this channel's
        stat cells — the hedge arming bar: when even the quickest
        backend's median cannot fit inside the remaining budget, the
        hedge is pure load on a cluster that is already slow. None =
        no telemetry yet (stats disabled / no completed calls);
        hedging then falls back to deadline-only gating."""
        cells = self.__dict__.get("_bs_cells")
        if not cells:
            return None
        best = None
        for _key, cell in cells.values():
            p = cell.recent_p50_us()
            if p > 0.0 and (best is None or p < best):
                best = p
        return None if best is None else best / 1e3

    def _on_backup_timer(self, cntl: Controller) -> None:
        """Send a duplicate request; first response wins
        (backup_request_ms, controller.cpp:331). Budget-aware arming
        (The Tail at Scale: hedged requests must never amplify
        overload): the hedge is suppressed when the retry token bucket
        is dry, and never armed when the remaining deadline sits under
        the fastest backend's recent p50 — a hedge that cannot finish
        in time is a guaranteed-wasted request. On first win the loser
        is cancelled client-side: its pending timers unschedule at
        completion, its LB selection and stat-cell record are swept as
        abandoned, and its attempt span closes with the verdict."""
        if address_call(cntl.correlation_id) is not cntl:
            return
        if self._budget_exhausted(cntl):
            # a backup issued at/after the deadline cannot win: the
            # timeout completion is already due (or racing this timer)
            nretry_suppressed.add(1)
            return
        rb = self._retry_budget
        if rb is not None and rb.throttled():
            # hedges amplify load exactly like retries: same bucket
            nretry_throttled.add(1)
            return
        dl = cntl.__dict__.get("_deadline_ns")
        remaining_ms = None if dl is None \
            else (dl - time.monotonic_ns()) / 1e6
        p50_ms = self._hedge_p50_ms()
        if remaining_ms is not None and p50_ms is not None \
                and remaining_ms < p50_ms:
            nhedge_suppressed.add(1)
            return
        cntl.used_backup = True
        # the arming evidence rides the attempt span (fabric storm
        # asserts no hedge was ever armed past budget from /rpcz)
        cntl.__dict__["_hedge_decision"] = (remaining_ms, p50_ms)
        self._issue_rpc(cntl)


def _settle_attempt_spans(cntl) -> None:
    """Settle the per-attempt child spans after the main client span
    finished: stragglers (the final attempt; a backup that lost the
    race) close with the call's verdict, and the set is submitted ONLY
    when the call used more than one attempt — a single-attempt call
    keeps exactly one client span, a retried/hedged call shows its
    fan-out in /rpcz and tools/trace.py critical paths."""
    from brpc_tpu.rpc.span import submit_span
    spans = cntl.__dict__.pop("_attempt_spans", None)
    if not spans:
        return
    now = time.monotonic_ns() // 1000
    for sp in spans:
        if not sp.end_us:
            sp.end_us = now
            sp.error_code = cntl.error_code
    if len(spans) > 1:
        for sp in spans:
            submit_span(sp)


class _PolicyView:
    """Read-only controller facade handed to RetryPolicy.do_retry /
    retry_backoff_s: the attempt's error is visible, every other
    attribute proxies to the real controller, and writes are rejected —
    so policies cannot race the completion paths. ``current_try`` may
    be pinned by the caller (the backoff path runs after the increment
    for the new attempt, but the contract exposes the index of the
    attempt that just failed)."""

    __slots__ = ("_cntl", "error_code", "error_text", "current_try")

    def __init__(self, cntl, code: int, text: str,
                 current_try: Optional[int] = None):
        object.__setattr__(self, "_cntl", cntl)
        object.__setattr__(self, "error_code", code)
        object.__setattr__(self, "error_text", text)
        object.__setattr__(self, "current_try",
                           cntl.current_try if current_try is None
                           else current_try)

    def failed(self) -> bool:
        return self.error_code != 0

    def __getattr__(self, name):
        return getattr(object.__getattribute__(self, "_cntl"), name)

    def __setattr__(self, name, value):
        raise AttributeError("retry policies see a read-only controller")


def _copy_buf(buf: IOBuf) -> IOBuf:
    out = IOBuf()
    out.append_buf(buf)
    return out
