"""Channel: the client stub (brpc/channel.{h,cpp}).

Owns protocol choice, timeout/retry/backup-request defaults, and the
connection to a single server (naming-service + load-balanced cluster
channels compose on top — see rpc/cluster_channel.py). The call path
mirrors Channel::CallMethod -> Controller::IssueRPC -> Socket::Write
(SURVEY.md §3.1): serialize, register correlation id, pack, enqueue,
arm deadline/backup timers, wait.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional

from brpc_tpu.butil.endpoint import EndPoint, str2endpoint
from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.fiber import TaskControl, global_control
from brpc_tpu.fiber.timer import global_timer
from brpc_tpu.protocol.proto import tpu_rpc_meta_pb2 as pb
from brpc_tpu.protocol.tpu_std import pack_message, serialize_payload
from brpc_tpu.rpc import errno_codes as berr
from brpc_tpu.rpc.controller import Controller, address_call, take_call
from brpc_tpu.transport.input_messenger import InputMessenger
from brpc_tpu.transport.socket import Socket, create_client_socket


@dataclass
class ChannelOptions:
    protocol: str = "tpu_std"
    connection_type: str = "single"      # single | pooled | short
    timeout_ms: Optional[float] = 1000.0
    max_retry: int = 3
    backup_request_ms: Optional[float] = None
    auth_token: str = ""




class Channel:
    def __init__(self, address: Optional[str | EndPoint] = None,
                 options: Optional[ChannelOptions] = None,
                 control: Optional[TaskControl] = None):
        self.options = options or ChannelOptions()
        self._control = control or global_control()
        self._messenger = InputMessenger(control=self._control)
        self._socket: Optional[Socket] = None
        self._socket_lock = threading.Lock()
        self._endpoint: Optional[EndPoint] = None
        if address is not None:
            self.init(address)

    def init(self, address: str | EndPoint) -> None:
        self._endpoint = (address if isinstance(address, EndPoint)
                          else str2endpoint(address))

    # ---------------------------------------------------------- connection
    def _get_socket(self) -> Socket:
        s = self._socket
        if s is not None and not s.failed:
            return s
        # connect OUTSIDE the lock: a slow/blackholed peer must not stall
        # every concurrent call on this channel
        new = create_client_socket(
            self._endpoint, on_input=self._messenger.on_new_messages,
            control=self._control)
        with self._socket_lock:
            cur = self._socket
            if cur is not None and not cur.failed:
                loser = new  # raced with another connector; keep theirs
            else:
                self._socket, loser = new, None
        if loser is not None:
            loser.set_failed(ConnectionError("duplicate connect discarded"))
            return self._socket
        return new

    def close(self) -> None:
        """Release the connection; the channel may be re-used (it will
        reconnect lazily)."""
        with self._socket_lock:
            s, self._socket = self._socket, None
        if s is not None and not s.failed:
            s.set_failed(ConnectionError("channel closed"))

    # ---------------------------------------------------------------- call
    def call(self, service_name: str, method_name: str, request: Any = b"",
             cntl: Optional[Controller] = None,
             done: Optional[Callable[[Controller], None]] = None,
             request_device_arrays: Optional[List] = None,
             response_class=None) -> Controller:
        """Begin an RPC; returns the Controller immediately. Wait with
        cntl.join() (thread) / await cntl.join_async() (fiber), or pass
        ``done`` for callback style — the async CallMethod triple."""
        cntl = cntl or Controller()
        cntl.start_us = time.monotonic_ns() // 1000
        if cntl.timeout_ms is None:
            cntl.timeout_ms = self.options.timeout_ms
        if cntl.max_retry is None:
            cntl.max_retry = self.options.max_retry
        if cntl.backup_request_ms is None:
            cntl.backup_request_ms = self.options.backup_request_ms
        cntl._done_cb = done
        cntl.auth_token = cntl.auth_token or self.options.auth_token
        if request_device_arrays:
            cntl.request_device_arrays = list(request_device_arrays)
        cntl.response_msg = response_class() if response_class is not None else None
        cntl._service_name = service_name
        cntl._method_name = method_name
        cntl._request_bytes = serialize_payload(request)
        cntl._register_call()
        self._issue_rpc(cntl)
        # deadline timer: final — no retry after it fires (HandleTimeout)
        if cntl.timeout_ms is not None:
            tid = global_timer().schedule_after(
                cntl.timeout_ms / 1e3, lambda: self._on_timeout(cntl))
            cntl._timer_ids.append(tid)
        if cntl.backup_request_ms is not None and cntl.backup_request_ms > 0:
            tid = global_timer().schedule_after(
                cntl.backup_request_ms / 1e3, lambda: self._on_backup_timer(cntl))
            cntl._timer_ids.append(tid)
        return cntl

    def call_sync(self, service_name: str, method_name: str, request: Any = b"",
                  cntl: Optional[Controller] = None, **kw) -> Controller:
        cntl = self.call(service_name, method_name, request, cntl=cntl, **kw)
        budget = None if cntl.timeout_ms is None else cntl.timeout_ms / 1e3 + 5.0
        cntl.join(budget)
        return cntl

    async def call_async(self, service_name: str, method_name: str,
                         request: Any = b"", cntl: Optional[Controller] = None,
                         **kw) -> Controller:
        cntl = self.call(service_name, method_name, request, cntl=cntl, **kw)
        budget = None if cntl.timeout_ms is None else cntl.timeout_ms / 1e3 + 5.0
        await cntl.join_async(budget)
        return cntl

    # ------------------------------------------------------------ internals
    def _issue_rpc(self, cntl: Controller) -> None:
        """Pick socket, pack, enqueue (Controller::IssueRPC,
        controller.cpp:1010)."""
        try:
            sock = self._get_socket()
        except (ConnectionError, OSError, ValueError) as e:
            self._maybe_retry(cntl, berr.EFAILEDSOCKET, str(e))
            return
        cntl.remote_side = sock.remote_endpoint
        cntl.local_side = sock.local_endpoint
        meta = pb.RpcMeta()
        meta.request.service_name = cntl._service_name
        meta.request.method_name = cntl._method_name
        meta.request.log_id = cntl.log_id
        if cntl.timeout_ms is not None:
            meta.request.timeout_ms = int(cntl.timeout_ms)
        if cntl.auth_token:
            meta.request.auth_token = cntl.auth_token
        meta.correlation_id = cntl.correlation_id
        meta.compress_type = cntl.compress_type
        if cntl.trace_id:
            meta.trace_id = cntl.trace_id
            meta.span_id = cntl.span_id
        use_lane = (bool(cntl.request_device_arrays)
                    and sock.conn.supports_device_lane)
        wire, lane = pack_message(
            meta, cntl._request_bytes, attachment=_copy_buf(cntl.request_attachment),
            device_arrays=cntl.request_device_arrays, device_lane=use_lane)
        if lane is not None:
            sock.write_device_payload(lane)
        sock.write(wire, on_done=lambda err: self._on_write_done(cntl, err))

    def _on_write_done(self, cntl: Controller, err: Optional[BaseException]):
        if err is None:
            return
        self._maybe_retry(cntl, berr.EFAILEDSOCKET, str(err))

    def _maybe_retry(self, cntl: Controller, code: int, text: str) -> None:
        """Retry on transport errors while the call is still live
        (OnVersionedRPCReturned's error branch, controller.cpp:634)."""
        if address_call(cntl.correlation_id) is not cntl:
            return  # already completed (response/timeout won)
        if cntl.current_try < cntl.max_retry:
            cntl.current_try += 1
            self._issue_rpc(cntl)
            return
        if take_call(cntl.correlation_id) is cntl:
            cntl.set_failed(code, text)
            cntl._complete()

    def _on_timeout(self, cntl: Controller) -> None:
        if take_call(cntl.correlation_id) is cntl:
            cntl.set_failed(berr.ERPCTIMEDOUT,
                            f"deadline {cntl.timeout_ms}ms exceeded")
            cntl._complete()

    def _on_backup_timer(self, cntl: Controller) -> None:
        """Send a duplicate request; first response wins
        (backup_request_ms, controller.cpp:331)."""
        if address_call(cntl.correlation_id) is not cntl:
            return
        cntl.used_backup = True
        self._issue_rpc(cntl)


def _copy_buf(buf: IOBuf) -> IOBuf:
    out = IOBuf()
    out.append_buf(buf)
    return out
