"""SimpleDataPool: reusable per-request session-local objects
(brpc/simple_data_pool.{h,cpp} + data_factory.h — ServerOptions.
session_local_data_factory). Objects are created by the factory on
demand, borrowed per request, reset (if the factory provides reset) and
returned for reuse — amortizing expensive per-request state."""

from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional


class SimpleDataPool:
    def __init__(self, factory: Callable[[], Any],
                 reset: Optional[Callable[[Any], None]] = None,
                 max_free: int = 128):
        self._factory = factory
        self._reset = reset
        self._max_free = max_free
        self._free: List[Any] = []
        self._lock = threading.Lock()
        self.ncreated = 0

    def borrow(self) -> Any:
        with self._lock:
            if self._free:
                return self._free.pop()
            self.ncreated += 1
        return self._factory()

    def give_back(self, obj: Any) -> None:
        if obj is None:
            return
        if self._reset is not None:
            try:
                self._reset(obj)
            except Exception:
                return    # a broken object is dropped, not recycled
        with self._lock:
            if len(self._free) < self._max_free:
                self._free.append(obj)

    @property
    def free_count(self) -> int:
        return len(self._free)
