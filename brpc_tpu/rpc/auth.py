"""Authenticator + Interceptor: pluggable per-connection auth and
per-request admission (brpc/authenticator.h, brpc/interceptor.h:26-37).

Client side: ``generate_credential()`` produces a string carried in the
request meta (the reference sends it with the first message on a
connection; here every tpu_std request carries it — the server still
verifies only once per connection and caches the AuthContext).

Server side: ``verify_credential(credential, remote_side)`` returns an
AuthContext (stored on the connection, visible as cntl.auth_context) or
raises AuthError to reject. The Interceptor runs after auth on every
request and may reject with (error_code, reason)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional, Tuple


class AuthError(Exception):
    """verify_credential rejection; the text goes back to the caller."""


@dataclass
class AuthContext:
    """Verified peer identity (brpc/authenticator.h AuthContext)."""
    user: str = ""
    group: str = ""
    roles: str = ""
    starter: str = ""
    is_service: bool = False
    extra: dict = field(default_factory=dict)


class Authenticator:
    def generate_credential(self) -> str:
        """Client: the credential string to send."""
        raise NotImplementedError

    def verify_credential(self, credential: str,
                          remote_side) -> AuthContext:
        """Server: verify; return the peer's AuthContext or raise
        AuthError. Called once per connection (first request), the
        result is cached on the socket."""
        raise NotImplementedError


class TokenAuthenticator(Authenticator):
    """Shared-secret bearer token (what ServerOptions.auth_token was)."""

    def __init__(self, token: str, user: str = "token-peer"):
        self._token = token
        self._user = user

    def generate_credential(self) -> str:
        return self._token

    def verify_credential(self, credential: str, remote_side) -> AuthContext:
        if credential != self._token:
            raise AuthError("authentication failed")
        return AuthContext(user=self._user)


# Interceptor (brpc/interceptor.h): callable(cntl) -> None to accept, or
# (error_code, reason) / raise InterceptorError to reject.
Interceptor = Callable[[object], Optional[Tuple[int, str]]]


class InterceptorError(Exception):
    def __init__(self, error_code: int, reason: str):
        super().__init__(reason)
        self.error_code = error_code
        self.reason = reason


def resolve_server_auth(options) -> Optional[Authenticator]:
    """ServerOptions.auth wins; auth_token is sugar for
    TokenAuthenticator (kept for compat)."""
    auth = getattr(options, "auth", None)
    if auth is not None:
        return auth
    token = getattr(options, "auth_token", None)
    if token is not None:
        return TokenAuthenticator(token)
    return None
