"""Shard-group serving: N worker processes behind one SO_REUSEPORT port.

One CPython process is GIL-bound to roughly one core of framework work,
so the single-process qps curve flat-lines as clients grow. The
reference escapes through ``-reuse_port`` (server.cpp StartInternal +
acceptor.cpp): every worker binds the same port with ``SO_REUSEPORT``
and the KERNEL spreads accepted connections across them — shared-nothing
per-core reactors, the same shape a TPU pod uses (one process per chip).

``Server.start(address, num_shards=N)`` builds a :class:`ShardGroup`:

  * the SUPERVISOR binds a placeholder reuseport socket (never listens)
    to pin the concrete port, then forks N workers;
  * each WORKER crosses the fork through the postfork-reset registry
    (butil/postfork.py) — fresh dispatcher, fresh TaskControl, fresh
    timer, fresh socket map, fresh bvar sampler, fresh IOBuf pool — and
    runs a fully private stack: its own GIL, its own event loop, its
    own bvar store. It binds the same port with ``reuse_port=1`` and
    serves;
  * each worker dumps its counters + latency reservoirs to a per-shard
    JSON file (the cross-process rpcz_dir pattern from the trace work);
    the dump doubles as its HEARTBEAT;
  * the supervisor restarts crashed/hung workers with jittered
    exponential backoff (re-binding the same port), and serves the
    MERGED observability view — ``/status``, ``/vars``, prometheus —
    from an admin endpoint, with per-shard breakdown behind ``?shard=``;
  * stop() drains gracefully: each shard closes its listener (the
    kernel stops routing new connections to it), finishes in-flight
    calls under the existing deadline machinery, flushes a final dump,
    and exits.

Surviving shards never notice a sibling's death: their connections,
fibers and counters live in their own process — the blast radius of a
crash is exactly one shard's connections, which clients re-dial onto a
live shard through the normal retry path.
"""

from __future__ import annotations

import json
import os
import random
import re
import signal
import socket as pysocket
import sys
import tempfile
import threading
import time
import traceback
from typing import Dict, List, Optional

from brpc_tpu.butil.endpoint import EndPoint, str2endpoint


class ShardGroupOptions:
    def __init__(self,
                 num_shards: int = 2,
                 admin_address: Optional[str] = None,
                 enable_admin: bool = True,
                 dump_interval_s: float = 0.3,
                 heartbeat_timeout_s: float = 10.0,
                 restart_backoff_s: float = 0.25,
                 restart_backoff_max_s: float = 5.0,
                 restart_jitter: float = 0.5,
                 drain_timeout_s: float = 5.0,
                 shard_dir: Optional[str] = None,
                 seed: Optional[int] = None):
        self.num_shards = num_shards
        # merged-observability endpoint (the supervisor's builtin
        # /status, /vars, /brpc_metrics). None = auto (same host,
        # ephemeral port); only honored when enable_admin.
        self.admin_address = admin_address
        self.enable_admin = enable_admin
        self.dump_interval_s = dump_interval_s
        # a shard whose dump went stale this long while its process is
        # still alive is considered hung and gets SIGKILL + restart;
        # <= 0 disables the hang check (crash detection stays on)
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.restart_backoff_s = restart_backoff_s
        self.restart_backoff_max_s = restart_backoff_max_s
        self.restart_jitter = restart_jitter
        self.drain_timeout_s = drain_timeout_s
        self.shard_dir = shard_dir           # None = private tempdir
        self.seed = seed                     # jitter reproducibility


# ------------------------------------------------------------------ dumps

def write_shard_dump(dirpath: str, index: int, server, seq: int) -> None:
    """One shard's observability snapshot, written atomically (tmp +
    rename) so the supervisor never reads a torn file. Carries the full
    /vars dump, the /status payload, and the RAW latency reservoirs per
    method — percentiles merge from pooled samples, not from averaged
    percentiles (averaging percentiles is wrong; pooling reservoirs is
    the same estimator LatencyRecorder itself uses)."""
    from brpc_tpu.builtin.flight_recorder import global_recorder
    from brpc_tpu.builtin.services import census_page_payload, status_page
    from brpc_tpu.bvar.variable import dump_exposed
    samples = {}
    for key, lr in server.method_status.items():
        samples[key] = lr._percentile.merged_samples()[:1024]
    doc = {
        "shard": index,
        "pid": os.getpid(),
        "seq": seq,
        "time": time.time(),
        "vars": dict(dump_exposed("")),
        "status": status_page(server),
        "latency_samples": samples,
        # flight-recorder state (bounded folded stacks + attribution):
        # the supervisor's /hotspots?mode=continuous merges these by
        # summing counters — same discipline as the vars/percentiles
        "hotspots": global_recorder().dump_state(),
        "census": census_page_payload(server),
    }
    if getattr(server, "_serving", None) is not None:
        from brpc_tpu.serving.service import serving_page_payload
        doc["serving"] = serving_page_payload(server)
    from brpc_tpu.transport.device_stats import (device_page_payload,
                                                 global_device_stats)
    if global_device_stats().rows():
        # device-lane state rides the dump only once a shard has moved
        # a device batch (the common host-only shard pays nothing);
        # the supervisor's /device merges these
        doc["device"] = device_page_payload(server)
    from brpc_tpu.bvar.series import series_enabled
    if series_enabled():
        # trend rings + incident ring ride the dump (bounded var
        # count: the supervisor's /timeline merges these per bucket)
        from brpc_tpu.builtin.services import timeline_page_payload
        doc["timeline"] = timeline_page_payload(server, max_vars=64)
    from brpc_tpu.traffic.capture import \
        global_recorder as traffic_recorder
    rec = traffic_recorder()
    if rec.capturing() or rec.corpus_paths():
        # traffic-capture state rides the dump: the supervisor's
        # /capture merges these and its download collects the per-pid
        # corpus files each shard names here
        doc["capture"] = rec.snapshot()
    from brpc_tpu.incident.manager import global_manager
    mgr = global_manager()
    if mgr.window_engaged or mgr.bundled or mgr.artifact_rows():
        # capture-on-anomaly state rides the dump once a shard has
        # armed or bundled anything: the supervisor's /incidents
        # merges these and its download resolves the per-shard
        # artifact paths named here
        from brpc_tpu.builtin.services import incidents_page_payload
        doc["incidents"] = incidents_page_payload(server)
    path = os.path.join(dirpath, f"shard-{index}.json")
    tmp = path + f".tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, default=str)
    os.replace(tmp, path)


_PCTL_RE = re.compile(r"(^|_)p\d")      # p50, latency_p99_us, ...


def _merge_stat_dict(dicts: List[dict]) -> dict:
    """Merge composite stat dicts (LatencyRecorder.get_value shapes):
    counts/qps sum, maxima/peaks take the max, averages / fractions /
    ratios / percentile FIELDS weight by count (equal weights when no
    counts exist, e.g. saturation panes). Weighted percentiles are a
    fallback for vars whose raw reservoirs were not dumped —
    method_status merges use the pooled-sample path instead
    (merged_method_status)."""
    out: dict = {}
    total = sum(d.get("count", 0) or 0 for d in dicts)
    for d in dicts:
        for k, v in d.items():
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                out.setdefault(k, v)
                continue
            if k in ("count", "qps") or k.endswith(("_count", "_qps")):
                out[k] = out.get(k, 0) + v
            elif "max" in k or "peak" in k:
                out[k] = max(out.get(k, v), v)
            elif k.endswith("limit") or k.endswith("threshold"):
                # a shard group's capacity headroom is its biggest
                # shard limit, not the sum (concurrency_limit et al;
                # limit_shed stays a summed counter below); the DAGOR
                # admission_threshold follows the same tightest-gate
                # rule
                out[k] = max(out.get(k, v), v)
            elif "tokens" in k:
                # retry budgets drain independently: the group's
                # health is its MOST drained bucket
                out[k] = min(out.get(k, v), v)
            elif ("avg" in k or "fraction" in k or "ratio" in k
                    or _PCTL_RE.search(k)):
                w = (d.get("count", 0) or 0) / total if total else \
                    1.0 / len(dicts)
                out[k] = out.get(k, 0.0) + v * w
            else:
                out[k] = out.get(k, 0) + v
    return {k: (round(v, 3) if isinstance(v, float) else v)
            for k, v in out.items()}


def merge_var_values(values: list, name: str = ""):
    """Merge one exposed variable's per-shard values: numbers sum
    (counters), dicts merge stat-wise, anything else keeps the first
    shard's reading (strings, None). ``name`` applies the scalar-gauge
    rules the saturation pane's dict merge uses — capacity limits take
    the max, retry-token gauges the min, fractions/ratios/usages the
    mean (summing two shards' 0.9 hit ratios to 1.8 is nonsense) — so
    merged /vars agrees with merged /status on the overload-control
    gauges AND with merged_timeline on every gauge series (the
    timeline's last-kind per-bucket merge calls THIS function with the
    same name, bvar/series.merge_timeline_states)."""
    nums = [v for v in values
            if isinstance(v, (int, float)) and not isinstance(v, bool)]
    if nums and len(nums) == len(values):
        if name.endswith("limit") or name.endswith("threshold"):
            # capacity limits AND the DAGOR admission threshold: the
            # group's headline is its tightest gate, not a sum
            return max(nums)
        if "tokens" in name:
            # -1 is the "no budget configured" sentinel
            # (retry_tokens_min): a shard without budgets must not
            # drag the group's most-drained reading to -1
            real = [v for v in nums if v >= 0]
            return min(real) if real else -1
        if ("ratio" in name or "usage" in name or "fraction" in name
                or name.endswith("_pct")):
            return round(sum(nums) / len(nums), 4)
        if "peak" in name or name.endswith("_max") or "_max_" in name:
            # windowed peaks are maxima, not additive flow
            return max(nums)
        s = sum(nums)
        return round(s, 3) if isinstance(s, float) else s
    dicts = [v for v in values if isinstance(v, dict)]
    if dicts and len(dicts) == len(values):
        return _merge_stat_dict(dicts)
    return values[0] if values else None


def _percentile(sorted_samples: List[float], ratio: float) -> float:
    if not sorted_samples:
        return 0.0
    idx = min(len(sorted_samples) - 1, int(ratio * len(sorted_samples)))
    return sorted_samples[idx]


class ShardAggregator:
    """Reads the per-shard dump files and serves the merged view. The
    merged numbers cover the SHARDS only (the supervisor process does
    no serving; mixing its own counters in would make 'merged equals
    the sum of the shard dumps' false)."""

    def __init__(self, dirpath: str, num_shards: int):
        self.dirpath = dirpath
        self.num_shards = num_shards
        self.group = None      # back-ref set by ShardGroup (supervisor)

    # ------------------------------------------------------------- reads
    def shard_dump(self, index: int) -> Optional[dict]:
        path = os.path.join(self.dirpath, f"shard-{index}.json")
        try:
            with open(path, encoding="utf-8") as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def read_dumps(self) -> List[dict]:
        out = []
        for i in range(self.num_shards):
            d = self.shard_dump(i)
            if d is not None:
                out.append(d)
        return out

    def heartbeat_age_s(self, index: int) -> Optional[float]:
        path = os.path.join(self.dirpath, f"shard-{index}.json")
        try:
            return max(0.0, time.time() - os.stat(path).st_mtime)
        except OSError:
            return None

    # ------------------------------------------------------------ merges
    def merged_vars(self, prefix: str = "") -> Dict[str, object]:
        dumps = self.read_dumps()
        names: List[str] = []
        seen = set()
        for d in dumps:
            for n in d.get("vars", {}):
                if n.startswith(prefix) and n not in seen:
                    seen.add(n)
                    names.append(n)
        out = {}
        for n in sorted(names):
            out[n] = merge_var_values(
                [d["vars"][n] for d in dumps if n in d.get("vars", {})],
                name=n)
        return out

    def merged_method_status(self, dumps: Optional[List[dict]] = None):
        """Per-method latency merged the honest way: counts/qps sum,
        max takes the max, avg weights by count, and percentiles come
        from the POOLED reservoir samples of every shard."""
        dumps = self.read_dumps() if dumps is None else dumps
        keys = sorted({k for d in dumps
                       for k in d.get("status", {}).get("method_status", {})})
        merged = {}
        for key in keys:
            stats = [d["status"]["method_status"][key] for d in dumps
                     if key in d.get("status", {}).get("method_status", {})]
            m = _merge_stat_dict(stats)
            pooled: List[float] = []
            for d in dumps:
                pooled.extend(d.get("latency_samples", {}).get(key, ()))
            pooled.sort()
            if pooled:
                m["latency_p50_us"] = round(_percentile(pooled, 0.5), 1)
                m["latency_p90_us"] = round(_percentile(pooled, 0.9), 1)
                m["latency_p99_us"] = round(_percentile(pooled, 0.99), 1)
                m["latency_p999_us"] = round(_percentile(pooled, 0.999), 1)
            merged[key] = m
        return merged

    def merged_status(self) -> dict:
        dumps = self.read_dumps()
        statuses = [d.get("status", {}) for d in dumps]
        services: Dict[str, list] = {}
        for st in statuses:
            services.update(st.get("services", {}))
        saturation = _merge_stat_dict(
            [st.get("saturation", {}) for st in statuses]) \
            if statuses else {}
        out = {
            "mode": "shard_group",
            "running": bool(dumps),
            "shards": self.num_shards,
            "shards_reporting": len(dumps),
            "concurrency": sum(st.get("concurrency", 0) for st in statuses),
            "processed": sum(st.get("processed", 0) for st in statuses),
            "errors": sum(st.get("errors", 0) for st in statuses),
            "services": services,
            "method_status": self.merged_method_status(dumps),
            "saturation": saturation,
            "shard_breakdown": {
                str(d.get("shard")): {
                    "pid": d.get("pid"),
                    "processed": d.get("status", {}).get("processed", 0),
                    "errors": d.get("status", {}).get("errors", 0),
                    "concurrency": d.get("status", {}).get("concurrency", 0),
                    "heartbeat_age_s": self.heartbeat_age_s(
                        d.get("shard", 0)),
                } for d in dumps},
        }
        if self.group is not None:
            out["endpoint"] = str(self.group.endpoint)
            out["supervisor"] = self.group.group_status()
        return out

    def prometheus_text(self) -> str:
        from brpc_tpu.bvar.prometheus import dump_prometheus_items
        return dump_prometheus_items(sorted(self.merged_vars().items()))

    def merged_hotspots(self) -> dict:
        """The group-wide continuous profile: per-shard flight-recorder
        states merged by summing sample counters (stall maxima take the
        max) — the same never-average-percentiles discipline, applied
        to profiles."""
        from brpc_tpu.builtin.flight_recorder import merge_dump_states
        return merge_dump_states(
            [d["hotspots"] for d in self.read_dumps()
             if d.get("hotspots")])

    def merged_serving(self) -> dict:
        """The group-wide serving view: per-shard engine payloads
        merged — counters/queue depths sum, the batch-size histogram
        and steps-by-group maps merge by key, KV occupancy averages
        over reporting shards (each shard owns an equal KV budget)."""
        dumps = [d["serving"] for d in self.read_dumps()
                 if d.get("serving") and d["serving"].get("enabled")]
        out: dict = {"mode": "shard_group", "shards_reporting": len(dumps),
                     "enabled": bool(dumps)}
        if not dumps:
            return out
        for key in ("waiting", "completed", "evicted", "shed",
                    "canceled", "tokens_out", "decode_steps"):
            out[key] = sum(d.get(key, 0) or 0 for d in dumps)
        out["running"] = sum(len(d.get("running", [])) for d in dumps)
        for key in ("batch_size_hist", "steps_by_worker_group"):
            merged: Dict[str, int] = {}
            for d in dumps:
                for k, v in (d.get(key) or {}).items():
                    merged[str(k)] = merged.get(str(k), 0) + v
            out[key] = dict(sorted(merged.items()))
        occ = [d.get("kv_occupancy") for d in dumps
               if d.get("kv_occupancy") is not None]
        out["kv_occupancy"] = round(sum(occ) / len(occ), 4) if occ else 0.0
        out["waiting_detail"] = [w for d in dumps
                                 for w in (d.get("waiting_detail")
                                           or ())][:32]
        # the flight-deck panes (per-method cells, TTFT/TPOT
        # reservoirs, step rings): counters sum, samples POOL with
        # percentiles recomputed — never averaged
        # (serving_stats.merge_serving_panes). Shards serve, so the
        # serving package is loaded in the supervisor that forked them;
        # sys.modules keeps this core module from importing the model
        # stack on a host-only group.
        panes = [d["stats"] for d in dumps if d.get("stats")]
        ss = sys.modules.get("brpc_tpu.serving.serving_stats")
        if panes and ss is not None:
            out["stats"] = ss.merge_serving_panes(panes)
        return out

    def merged_device(self) -> dict:
        """The group-wide /device view: per-shard device payloads
        merged — counters sum, latency samples POOL, conn panes concat
        (transport/device_stats.merge_device_payloads)."""
        from brpc_tpu.transport.device_stats import merge_device_payloads
        return merge_device_payloads(
            [d["device"] for d in self.read_dumps() if d.get("device")])

    def merged_timeline(self, names=None, prefix: str = "") -> dict:
        """The group-wide /timeline: per-shard trend-ring dumps merged
        per epoch-second bucket — counters sum, maxima max, quantile
        series pool their per-field worst case (never averaged),
        gauges through merge_var_values — plus every shard's incidents
        tagged with their shard index
        (bvar/series.merge_timeline_states)."""
        from brpc_tpu.bvar.series import merge_timeline_states
        return merge_timeline_states(
            [(d.get("shard"), d["timeline"]) for d in self.read_dumps()
             if d.get("timeline")],
            names=names, prefix=prefix)

    def merged_capture(self) -> dict:
        """The group-wide /capture view: per-shard recorder snapshots
        (counters sum, files union) plus the control file's last
        command so an operator can see what the shards were told."""
        dumps = self.read_dumps()
        caps = [(d.get("shard"), d["capture"]) for d in dumps
                if d.get("capture")]
        out: dict = {"mode": "shard_group",
                     "shards_reporting": len(caps),
                     "active": any(c.get("active") for _, c in caps)}
        for key in ("sampled", "written", "written_bytes",
                    "dropped_queue", "dropped_budget", "rotations",
                    "deleted_files", "pending"):
            out[key] = sum(c.get(key, 0) or 0 for _, c in caps)
        files = {}
        for _, c in caps:
            for f in c.get("files", ()):
                files[f["path"]] = f
        out["files"] = [files[p] for p in sorted(files)]
        out["shard_breakdown"] = {
            str(i): {"active": c.get("active"),
                     "written": c.get("written"),
                     "pid": c.get("pid")} for i, c in caps}
        ctl = self._read_capture_control()
        if ctl is not None:
            out["control"] = ctl
        return out

    def merged_incidents(self) -> dict:
        """The group-wide /incidents view: per-shard incident sections
        concatenated (each artifact row tagged with its shard index,
        sorted by open stamp then shard — the PR 13 incident-merge
        discipline), counters and byte totals summed, the open-window
        count across shards."""
        dumps = self.read_dumps()
        secs = [(d.get("shard"), d["incidents"]) for d in dumps
                if d.get("incidents")]
        out: dict = {"mode": "shard_group",
                     "shards_reporting": len(secs),
                     "enabled": any(s.get("enabled") for _, s in secs),
                     "open": sum(int(s.get("open") or 0)
                                 for _, s in secs)}
        for key in ("total", "evicted", "skipped", "artifact_bytes"):
            out[key] = sum(s.get(key, 0) or 0 for _, s in secs)
        rows = []
        for shard, s in secs:
            for row in s.get("artifacts") or ():
                r = dict(row)
                r["shard"] = shard
                rows.append(r)
        rows.sort(key=lambda r: (r.get("opened_t") or 0,
                                 r.get("shard") or 0))
        out["artifacts"] = rows
        out["shard_breakdown"] = {
            str(i): {"open": s.get("open"), "total": s.get("total"),
                     "artifact_bytes": s.get("artifact_bytes"),
                     "last_error": s.get("last_error") or ""}
            for i, s in secs}
        return out

    def capture_paths(self) -> List[str]:
        """Every corpus file the shards named in their dumps — the
        supervisor download's merge set."""
        paths = set()
        for d in self.read_dumps():
            for f in (d.get("capture") or {}).get("files", ()):
                paths.add(f["path"])
        return sorted(p for p in paths if os.path.exists(p))

    def _read_capture_control(self) -> Optional[dict]:
        try:
            with open(os.path.join(self.dirpath, "capture-control.json"),
                      encoding="utf-8") as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def merged_census(self) -> dict:
        """The group-wide resource census: per-subsystem stat dicts
        merged with the shared counter/ratio/max rules, totals and the
        connection roll-up summed across shards."""
        censuses = [d["census"] for d in self.read_dumps()
                    if d.get("census")]
        subs: Dict[str, list] = {}
        for c in censuses:
            for name, d in c.get("subsystems", {}).items():
                subs.setdefault(name, []).append(d)
        out = {
            "mode": "shard_group",
            "shards_reporting": len(censuses),
            "subsystems": {n: _merge_stat_dict(ds)
                           for n, ds in sorted(subs.items())},
            "total_bytes": sum(c.get("total_bytes", 0) or 0
                               for c in censuses),
            "connections": _merge_stat_dict(
                [c.get("connections", {}) for c in censuses]),
        }
        return out


# ------------------------------------------------------------- the group

def _apply_capture_control(shard_dir: str, seen_seq: int) -> int:
    """Shard side of the supervisor's /capture control plane: apply
    the control file's command once per sequence bump. Failures are
    contained — serving must not die for a capture knob."""
    try:
        with open(os.path.join(shard_dir, "capture-control.json"),
                  encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return seen_seq
    seq = int(doc.get("seq", 0) or 0)
    if seq <= seen_seq:
        return seen_seq
    try:
        from brpc_tpu.traffic.capture import start_capture, stop_capture
        if doc.get("action") == "start":
            kw = {}
            if doc.get("rate") not in (None, ""):
                kw["default_rate"] = float(doc["rate"])
            if doc.get("max_per_second") not in (None, ""):
                kw["max_per_second"] = int(doc["max_per_second"])
            if doc.get("rotate_mb") not in (None, ""):
                kw["rotate_bytes"] = int(doc["rotate_mb"]) << 20
            if doc.get("disk_budget_mb") not in (None, ""):
                kw["disk_budget_bytes"] = \
                    int(doc["disk_budget_mb"]) << 20
            start_capture(dir=doc.get("dir"), **kw)
        elif doc.get("action") == "stop":
            stop_capture()
    except (ValueError, OSError):
        pass
    return seq


class _ShardState:
    __slots__ = ("index", "pid", "state", "restarts", "consecutive",
                 "restart_at", "started_at", "hb_sig", "hb_seen")

    def __init__(self, index: int):
        self.index = index
        self.pid = 0
        self.state = "starting"        # starting|running|restarting
        self.restarts = 0              # lifetime restarts
        self.consecutive = 0           # crashes since last healthy spell
        self.restart_at = 0.0          # monotonic deadline for refork
        self.started_at = 0.0
        self.hb_sig = None             # last observed dump mtime_ns
        self.hb_seen = 0.0             # monotonic time hb_sig last moved

    def to_dict(self) -> dict:
        return {"index": self.index, "pid": self.pid, "state": self.state,
                "restarts": self.restarts,
                "uptime_s": round(time.monotonic() - self.started_at, 1)
                if self.started_at else 0.0}


class ShardGroup:
    """Supervisor for a reuseport shard group (see module doc)."""

    # a shard considered healthy for this long resets the crash streak
    _HEALTHY_AFTER_S = 5.0

    def __init__(self, server, address, options: Optional[ShardGroupOptions] = None):
        self.server = server
        self.options = options or ShardGroupOptions()
        ep = address if isinstance(address, EndPoint) else str2endpoint(address)
        if ep.scheme != "tcp":
            raise ValueError(
                f"shard groups need SO_REUSEPORT, a tcp:// kernel "
                f"feature; got {ep.scheme}://")
        if self.options.num_shards < 1:
            raise ValueError("num_shards must be >= 1")
        self._requested_ep = ep
        self._endpoint: Optional[EndPoint] = None
        self._placeholder: Optional[pysocket.socket] = None
        self._shards: List[_ShardState] = [
            _ShardState(i) for i in range(self.options.num_shards)]
        self._lock = threading.Lock()
        self._stopping = False
        self._monitor: Optional[threading.Thread] = None
        self._admin_server = None
        self._admin_endpoint: Optional[EndPoint] = None
        self._rng = random.Random(self.options.seed)
        self._capture_ctl_seq = 0
        self.shard_dir = self.options.shard_dir
        self._own_shard_dir = self.options.shard_dir is None
        self.aggregator: Optional[ShardAggregator] = None

    # ---------------------------------------------------------- lifecycle
    def start(self) -> EndPoint:
        """Bind the port, fork the workers, start the monitor and the
        admin endpoint; returns the data-plane endpoint."""
        ep = self._requested_ep
        # the placeholder socket pins the concrete port for the whole
        # group lifetime WITHOUT serving: it never listens, so the
        # kernel's reuseport balancing only ever sees the workers'
        # listening sockets. Restarted shards re-bind the same port
        # because this socket keeps the reuseport group alive even if
        # every worker is momentarily dead.
        sock = pysocket.socket(pysocket.AF_INET, pysocket.SOCK_STREAM)
        sock.setsockopt(pysocket.SOL_SOCKET, pysocket.SO_REUSEADDR, 1)
        sock.setsockopt(pysocket.SOL_SOCKET, pysocket.SO_REUSEPORT, 1)
        sock.bind((ep.host or "127.0.0.1", ep.port))
        host, port = sock.getsockname()[:2]
        self._placeholder = sock
        self._endpoint = EndPoint("tcp", host, port, ())
        if self.shard_dir is None:
            self.shard_dir = tempfile.mkdtemp(prefix="brpc-tpu-shards-")
        else:
            os.makedirs(self.shard_dir, exist_ok=True)
        self.aggregator = ShardAggregator(self.shard_dir,
                                          self.options.num_shards)
        self.aggregator.group = self
        try:
            for st in self._shards:
                self._fork_shard(st)
            self._monitor = threading.Thread(target=self._monitor_loop,
                                             name="shard_supervisor",
                                             daemon=True)
            self._monitor.start()
            if self.options.enable_admin:
                self._start_admin()
        except BaseException:
            # a failure past the first fork (admin port in use, monitor
            # thread limit) must not leak live workers serving a port
            # the caller believes never started — Server.stop() would
            # be a no-op since _running was never set
            self.stop()
            raise
        return self._endpoint

    def _start_admin(self) -> None:
        from brpc_tpu.rpc.server import Server, ServerOptions
        admin = Server(ServerOptions(enable_builtin_services=True))
        admin.shard_aggregator = self.aggregator
        addr = self.options.admin_address or \
            f"tcp://{self._endpoint.host}:0"
        self._admin_endpoint = admin.start(addr)
        self._admin_server = admin

    @property
    def endpoint(self) -> Optional[EndPoint]:
        return self._endpoint

    @property
    def admin_endpoint(self) -> Optional[EndPoint]:
        return self._admin_endpoint

    def shard_pids(self) -> List[int]:
        with self._lock:
            return [st.pid for st in self._shards if st.state == "running"]

    def write_capture_control(self, action: str, params: dict) -> int:
        """The supervisor's capture control plane: shards have no
        admin port of their own, but they already visit the shard dir
        every dump tick — a sequenced control file there reaches all
        of them within one dump interval, atomically (tmp + rename,
        the dump files' own discipline). Returns the new sequence."""
        if action not in ("start", "stop"):
            raise ValueError(f"unknown capture action {action!r}")
        with self._lock:
            self._capture_ctl_seq += 1
            seq = self._capture_ctl_seq
        doc = {"seq": seq, "action": action}
        if action == "start":
            # one shared dir: per-pid file names keep shards apart
            doc["dir"] = params.get("dir") or \
                os.path.join(self.shard_dir, "capture")
            for k in ("rate", "max_per_second", "rotate_mb",
                      "disk_budget_mb"):
                if params.get(k) not in (None, ""):
                    doc[k] = params[k]
        path = os.path.join(self.shard_dir, "capture-control.json")
        tmp = path + f".tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        return seq

    def group_status(self) -> dict:
        with self._lock:
            return {"stopping": self._stopping,
                    "admin": str(self._admin_endpoint)
                    if self._admin_endpoint else None,
                    "shard_dir": self.shard_dir,
                    "shards": [st.to_dict() for st in self._shards]}

    def stop(self) -> None:
        """Graceful drain: SIGTERM every shard (each closes its
        listener, finishes in-flight calls, flushes a last dump and
        exits), escalate to SIGKILL past the drain budget."""
        with self._lock:
            if self._stopping:
                return
            self._stopping = True
            pids = [st.pid for st in self._shards if st.pid > 0]
        for pid in pids:
            try:
                os.kill(pid, signal.SIGTERM)
            except OSError:
                pass
        deadline = time.monotonic() + self.options.drain_timeout_s + 2.0
        live = set(pids)
        while live and time.monotonic() < deadline:
            for pid in list(live):
                try:
                    done, _ = os.waitpid(pid, os.WNOHANG)
                    if done:
                        live.discard(pid)
                except OSError:
                    live.discard(pid)
            if live:
                time.sleep(0.02)
        for pid in live:
            try:
                os.kill(pid, signal.SIGKILL)
                os.waitpid(pid, 0)
            except OSError:
                pass
        if self._admin_server is not None:
            try:
                self._admin_server.stop()
                self._admin_server.join(1.0)
            except Exception:
                pass
        if self._placeholder is not None:
            try:
                self._placeholder.close()
            except OSError:
                pass

    def join(self, timeout_s: float = 10.0) -> None:
        t = self._monitor
        if t is not None:
            t.join(timeout_s)

    # ------------------------------------------------------------ forking
    def _fork_shard(self, st: _ShardState) -> None:
        pid = os.fork()
        if pid == 0:
            # ---- CHILD: never returns. The postfork-reset registry
            # already ran inside fork(); every singleton accessor now
            # rebuilds privately.
            try:
                self._child_main(st.index)
            except BaseException:
                try:
                    traceback.print_exc(file=sys.stderr)
                    sys.stderr.flush()
                except Exception:
                    pass
            finally:
                os._exit(1)
        with self._lock:
            st.pid = pid
            st.state = "running"
            st.started_at = time.monotonic()
            st.hb_sig = None
            st.hb_seen = st.started_at
            stopping = self._stopping
        if stopping:
            # raced stop(): its SIGTERM sweep already ran and would
            # never reach this brand-new child — and stop() may have
            # RETURNED, so nobody else will reap it either. SIGKILL
            # (the child is milliseconds old, it has nothing to drain)
            # and wait right here, so a restart landing mid-shutdown
            # can neither keep the port served behind the group's back
            # nor linger as a zombie for the supervisor's lifetime.
            try:
                os.kill(pid, signal.SIGKILL)
                os.waitpid(pid, 0)
            except OSError:
                pass

    def _backoff_s(self, st: _ShardState) -> float:
        base = min(self.options.restart_backoff_max_s,
                   self.options.restart_backoff_s
                   * (2 ** max(0, st.consecutive - 1)))
        # jitter DESYNCHRONIZES restarts: N shards felled by one cause
        # (OOM killer sweep) must not re-bind and re-crash in lockstep
        return base * (1.0 + self.options.restart_jitter
                       * self._rng.random())

    # ------------------------------------------------------------ monitor
    def _monitor_loop(self) -> None:
        hb = self.options.heartbeat_timeout_s
        # Event-parked tick (not time.sleep): the flight recorder's
        # idle classifier must see this supervisor thread as waiting
        park = threading.Event()
        while True:
            with self._lock:
                if self._stopping:
                    return
                shards = list(self._shards)
            now = time.monotonic()
            for st in shards:
                if st.state == "running":
                    crashed = False
                    try:
                        done, _ = os.waitpid(st.pid, os.WNOHANG)
                        crashed = bool(done)
                    except ChildProcessError:
                        crashed = True
                    except OSError:
                        pass
                    if not crashed and hb > 0 and self.aggregator:
                        # hang detection on the MONITOR's monotonic
                        # clock: a dump whose mtime moved is a fresh
                        # heartbeat; one that hasn't moved for hb while
                        # the process lives means hung. (Comparing
                        # wall-clock dump age directly would SIGKILL
                        # every healthy shard at once after an NTP
                        # step or VM suspend/resume.)
                        try:
                            sig = os.stat(os.path.join(
                                self.shard_dir,
                                f"shard-{st.index}.json")).st_mtime_ns
                        except OSError:
                            sig = None
                        if sig is not None and sig != st.hb_sig:
                            st.hb_sig = sig
                            st.hb_seen = now
                        elif now - st.hb_seen > hb \
                                and now - st.started_at > hb:
                            # alive but not dumping: hung. SIGKILL and
                            # reap on the next tick like any crash.
                            try:
                                os.kill(st.pid, signal.SIGKILL)
                            except OSError:
                                pass
                    if crashed:
                        with self._lock:
                            if self._stopping:
                                return
                            if now - st.started_at > self._HEALTHY_AFTER_S:
                                st.consecutive = 0
                            st.consecutive += 1
                            st.restarts += 1
                            st.state = "restarting"
                            # the pid is reaped and may be RECYCLED by
                            # the OS at any moment: zero it so a
                            # concurrent stop() can never SIGTERM an
                            # unrelated process that inherited it
                            st.pid = 0
                            st.restart_at = now + self._backoff_s(st)
                elif st.state == "restarting" and now >= st.restart_at:
                    self._fork_shard(st)
            park.wait(0.05)

    # -------------------------------------------------------------- child
    def _child_main(self, index: int) -> None:
        """Worker body. Runs with a freshly reset singleton registry:
        builds its private serving stack, binds the shared port with
        SO_REUSEPORT, heartbeats via the dump file, and drains on
        SIGTERM."""
        # inherited supervisor fds we can name: close OUR copies so a
        # worker never holds the admin listener or the placeholder open
        # past the supervisor's close (closing a dup does not release
        # the parent's port reservation)
        admin = self._admin_server
        admin_sock = getattr(getattr(admin, "_listener", None), "_sock",
                             None) if admin is not None else None
        for obj in (self._placeholder, admin_sock):
            try:
                if obj is not None:
                    obj.close()
            except OSError:
                pass
        stop_ev = threading.Event()
        signal.signal(signal.SIGTERM, lambda *_: stop_ev.set())
        signal.signal(signal.SIGINT, lambda *_: stop_ev.set())

        server = self.server
        server._postfork_child_reset()
        server.shard_index = index
        from brpc_tpu.bvar.reducer import PassiveStatus
        PassiveStatus(lambda: index).expose("shard_index")
        ep = EndPoint("tcp", self._endpoint.host, self._endpoint.port,
                      (("reuse_port", "1"),))
        server.start(ep)

        # SIGTERM must land on OUR drain path, not the generic
        # stop-only handler server.start may have installed
        signal.signal(signal.SIGTERM, lambda *_: stop_ev.set())

        parent = os.getppid()
        seq = 0
        ctl_seen = 0
        interval = max(0.05, self.options.dump_interval_s)
        while not stop_ev.is_set():
            seq += 1
            try:
                write_shard_dump(self.shard_dir, index, server, seq)
            except OSError:
                pass   # disk hiccup: serving must not die for a dump
            ctl_seen = _apply_capture_control(self.shard_dir, ctl_seen)
            if os.getppid() != parent:
                break  # supervisor died without SIGTERM: orphan exit
            stop_ev.wait(interval)

        # graceful drain: close the listener FIRST (the kernel drops us
        # from the reuseport group; new connections go to siblings),
        # then let in-flight calls finish under the deadline machinery
        server.stop()
        server.join(self.options.drain_timeout_s)
        try:
            write_shard_dump(self.shard_dir, index, server, seq + 1)
        except OSError:
            pass
        from brpc_tpu.rpc.span import global_store
        global_store.flush()
        os._exit(0)
