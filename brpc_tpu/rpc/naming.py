"""Naming services: url -> live server list (brpc/naming_service.h:36,
SURVEY.md §2.6).

A NamingService runs in its own fiber (details/naming_service_thread.*)
and pushes full server lists through actions.reset_servers(). Builtins:

  list://ep1,ep2,...      static list (test/brpc_naming_service style)
  file://path             one endpoint per line, re-read periodically
  dns://host:port         resolved via socket.getaddrinfo
  mesh://                 one endpoint per local JAX device — the pod
                          fabric enumerated as servers (the `mesh://` NS
                          from SURVEY.md §7 stage 7); multi-host expands
                          via jax.process_count/device coords
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Dict, List, Optional

from brpc_tpu.butil.endpoint import EndPoint, str2endpoint
from brpc_tpu.fiber import TaskControl, global_control, sleep


class NamingServiceActions:
    """Receives server-list updates (NamingServiceActions::ResetServers)."""

    def reset_servers(self, servers: List[EndPoint]) -> None:
        raise NotImplementedError


class NamingService:
    def run(self, param: str, actions: NamingServiceActions, stop_event) -> None:
        """Async or sync; loops until stop_event.is_set()."""
        raise NotImplementedError


class StaticNamingService(NamingService):
    """list:// — servers fixed at init."""

    async def run(self, param, actions, stop_event):
        eps = [str2endpoint(p.strip()) for p in param.split(",") if p.strip()]
        actions.reset_servers(eps)


class FileNamingService(NamingService):
    interval_s = 1.0

    async def run(self, param, actions, stop_event):
        last = None
        while not stop_event.is_set():
            try:
                with open(param) as f:
                    lines = [l.strip() for l in f if l.strip()
                             and not l.startswith("#")]
            except OSError:
                lines = []
            if lines != last:
                last = lines
                actions.reset_servers([str2endpoint(l) for l in lines])
            await sleep(self.interval_s)


class DnsNamingService(NamingService):
    interval_s = 5.0

    async def run(self, param, actions, stop_event):
        import socket as pysocket
        ep = str2endpoint(param, default_scheme="tcp")
        last = None
        while not stop_event.is_set():
            try:
                infos = pysocket.getaddrinfo(ep.host, ep.port,
                                             pysocket.AF_INET,
                                             pysocket.SOCK_STREAM)
                ips = sorted({i[4][0] for i in infos})
            except OSError:
                ips = []
            if ips != last:
                last = ips
                actions.reset_servers(
                    [EndPoint("tcp", ip, ep.port) for ip in ips])
            await sleep(self.interval_s)


class MeshNamingService(NamingService):
    """mesh:// — every local JAX device is a server endpoint; the param is
    the base name the per-device tpu:// listeners were started under,
    e.g. mesh://podsvc:9000 -> tpu://podsvc:9000#device=K for each K."""

    async def run(self, param, actions, stop_event):
        import jax
        base = str2endpoint(param, default_scheme="tpu")
        eps = [EndPoint("tpu", base.host, base.port).with_extras(device=d.id)
               for d in jax.devices()]
        actions.reset_servers(eps)


class RemoteFileNamingService(NamingService):
    """remotefile:// — poll a server list over HTTP (the reference's
    remote_file_naming_service + the generic shape of its consul/nacos
    pollers: GET an endpoint, parse one server per line).
    Param: ``host:port/path``."""

    interval_s = 2.0

    async def run(self, param, actions, stop_event):
        import http.client
        hostport, _, path = param.partition("/")
        host, _, port = hostport.partition(":")
        last = None
        while not stop_event.is_set():
            lines: List[str] = []
            try:
                conn = http.client.HTTPConnection(host, int(port or 80),
                                                  timeout=3)
                conn.request("GET", "/" + path)
                resp = conn.getresponse()
                if resp.status == 200:
                    lines = [ln.strip() for ln in
                             resp.read().decode().splitlines()
                             if ln.strip() and not ln.startswith("#")]
                conn.close()
            except (OSError, ValueError):
                pass   # keep the last good list on fetch failure
            if lines and lines != last:
                last = lines
                eps = []
                for ln in lines:
                    try:
                        eps.append(str2endpoint(ln))
                    except ValueError:
                        # one malformed line must not kill the poller
                        logging.warning("remotefile NS: bad line %r", ln)
                actions.reset_servers(eps)
            await sleep(self.interval_s)


def _http_get(hostport: str, path: str, timeout: float = 3.0):
    """GET host:port/path -> (status, body bytes) or (0, b"") on any
    transport-level failure — including http.client.HTTPException
    (BadStatusLine / IncompleteRead on a registry restarting
    mid-response), which must not kill the polling fiber. Shared by the
    registry-polling naming services."""
    import http.client

    host, _, port = hostport.partition(":")
    try:
        conn = http.client.HTTPConnection(host, int(port or 80),
                                          timeout=timeout)
        conn.request("GET", path)
        resp = conn.getresponse()
        body = resp.read()
        conn.close()
        return resp.status, body
    except (OSError, ValueError, http.client.HTTPException):
        return 0, b""


class _RegistryNamingService(NamingService):
    """Shared loop for HTTP-registry pollers (consul/nacos/discovery —
    the reference's policy/*_naming_service.cpp family): GET the
    registry path, parse to endpoints, push on change; transport
    failures and malformed payloads keep the last good list. Subclasses
    supply ``path(name)`` and ``parse(body, name) -> eps | None``."""

    interval_s = 2.0

    def path(self, name: str) -> str:
        raise NotImplementedError

    def parse(self, body: bytes, name: str):
        raise NotImplementedError

    async def run(self, param, actions, stop_event):
        hostport, _, name = param.partition("/")
        last = None
        while not stop_event.is_set():
            status, body = _http_get(hostport, self.path(name))
            if status == 200:
                try:
                    eps = self.parse(body, name)
                except (ValueError, TypeError, KeyError):
                    eps = None   # malformed payload: keep last good list
                if eps is not None and eps != last:
                    last = eps
                    actions.reset_servers(eps)
            await sleep(self.interval_s)


class ConsulNamingService(_RegistryNamingService):
    """consul://agent-host:port/service-name — polls the Consul health
    API (policy/consul_naming_service.cpp): only passing instances are
    listed; Service.Address falls back to Node.Address when empty."""

    def path(self, name):
        from urllib.parse import quote
        return f"/v1/health/service/{quote(name)}?stale&passing"

    def parse(self, body, name):
        import json as _json
        eps = []
        for entry in _json.loads(body):
            svc = entry.get("Service", {})
            addr = svc.get("Address") or \
                entry.get("Node", {}).get("Address")
            port = svc.get("Port")
            if addr and port:
                eps.append(EndPoint("tcp", addr, int(port)))
        return eps


class NacosNamingService(_RegistryNamingService):
    """nacos://server-host:port/serviceName — polls the Nacos instance
    list (policy/nacos_naming_service.cpp): healthy+enabled instances
    only; weight rides the endpoint extras for weighted LBs."""

    def path(self, name):
        from urllib.parse import quote
        return f"/nacos/v1/ns/instance/list?serviceName={quote(name)}"

    def parse(self, body, name):
        import json as _json
        eps = []
        for h in _json.loads(body).get("hosts", []):
            if not (h.get("healthy", True) and h.get("enabled", True)):
                continue
            ep = EndPoint("tcp", h["ip"], int(h["port"]))
            w = h.get("weight")
            if w is not None:
                # Nacos weights are floats; the weighted LBs read extra
                # 'w' as an int (load_balancer.py wrr/wr convention).
                # weight<=0 means "drained" in Nacos — skip the host
                # like unhealthy/disabled ones. Malformed/inf weights
                # fall back to 1 rather than killing the polling loop.
                try:
                    wf = float(w)
                except (TypeError, ValueError):
                    wf = 1.0
                if wf != wf:       # NaN: int(nan) would raise and
                    wf = 1.0       # freeze the whole poll result
                if wf <= 0:
                    continue
                # cap: wrr materializes weight copies per server, so an
                # absurd registry value must not OOM the reset path
                ep = ep.with_extras(
                    w=1 if wf == float("inf") else min(10000, max(1, int(wf))))
            eps.append(ep)
        return eps


class DiscoveryNamingService(_RegistryNamingService):
    """discovery://server-host:port/appid — polls a bilibili-discovery
    registry (policy/discovery_naming_service.cpp): instances carry
    scheme-prefixed addrs; status==1 (UP) only."""

    def path(self, name):
        from urllib.parse import quote
        return f"/discovery/fetchs?appid={quote(name)}&status=1"

    def parse(self, body, name):
        import json as _json
        doc = _json.loads(body)
        if doc.get("code", 0) != 0:
            return None
        eps = []
        app = doc.get("data", {}).get(name, {})
        for inst in app.get("instances", []):
            if inst.get("status", 1) != 1:
                continue
            for addr in inst.get("addrs", []):
                _, _, hp = addr.partition("://")
                host, _, port = hp.partition(":")
                if host and port:
                    eps.append(EndPoint("tcp", host, int(port)))
                    break   # one addr per instance
        return eps


_registry: Dict[str, NamingService] = {}


def register_naming_service(scheme: str, ns: NamingService) -> None:
    _registry[scheme] = ns


def get_naming_service(scheme: str) -> NamingService:
    if not _registry:
        _registry.update({
            "list": StaticNamingService(),
            "file": FileNamingService(),
            "dns": DnsNamingService(),
            "mesh": MeshNamingService(),
            "remotefile": RemoteFileNamingService(),
            "consul": ConsulNamingService(),
            "nacos": NacosNamingService(),
            "discovery": DiscoveryNamingService(),
        })
    ns = _registry.get(scheme)
    if ns is None:
        raise ValueError(f"no naming service for scheme {scheme!r}")
    return ns


class NamingServiceThread:
    """Runs one naming service in a fiber and fans updates out to
    watchers (details/naming_service_thread.{h,cpp})."""

    def __init__(self, url: str, control: Optional[TaskControl] = None):
        scheme, _, param = url.partition("://")
        self.url = url
        self._ns = get_naming_service(scheme)
        self._param = param
        self._control = control or global_control()
        self._watchers: List[Callable[[List[EndPoint]], None]] = []
        self._servers: List[EndPoint] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._first_update = threading.Event()
        # freshness telemetry for /backends: how many list resets this
        # thread has delivered and when the last one landed (a stale
        # naming feed explains a frozen backend set at a glance)
        self._revision = 0
        self._last_update_mono: Optional[float] = None

        outer = self

        class _Actions(NamingServiceActions):
            def reset_servers(self, servers):
                import time as _time
                with outer._lock:
                    outer._servers = list(servers)
                    watchers = list(outer._watchers)
                    outer._revision += 1
                    outer._last_update_mono = _time.monotonic()
                # notify watchers BEFORE releasing wait_first_update():
                # a ClusterChannel constructor blocked on that event must
                # find its LB already seeded when it wakes, or its first
                # call races an empty server list. One watcher blowing up
                # must neither starve the others nor leave the event
                # unset forever.
                try:
                    for w in watchers:
                        try:
                            w(list(servers))
                        except Exception:
                            logging.exception("naming watcher failed")
                finally:
                    outer._first_update.set()

        self._fiber = self._control.spawn(
            self._ns.run, self._param, _Actions(), self._stop,
            name=f"naming_{scheme}")

    def watch(self, cb: Callable[[List[EndPoint]], None]) -> None:
        with self._lock:
            self._watchers.append(cb)
            servers = list(self._servers)
        if self._first_update.is_set():
            cb(servers)

    def servers(self) -> List[EndPoint]:
        with self._lock:
            return list(self._servers)

    def revision(self) -> int:
        """Server-list resets delivered so far (0 = never updated)."""
        with self._lock:
            return self._revision

    def last_update_age_s(self) -> Optional[float]:
        """Seconds since the last list reset; None = never updated."""
        import time as _time
        with self._lock:
            if self._last_update_mono is None:
                return None
            return round(_time.monotonic() - self._last_update_mono, 3)

    def wait_first_update(self, timeout_s: float = 5.0) -> bool:
        return self._first_update.wait(timeout_s)

    def stop(self) -> None:
        self._stop.set()
