"""Combo channels (SURVEY.md §2.6):

  ParallelChannel  — one call fans out to N sub-channels, responses merge
                     (parallel_channel.h: CallMapper :94, ResponseMerger
                     :127, fail_limit :168).
  SelectiveChannel — LB over heterogeneous sub-channels with
                     retry-elsewhere (selective_channel.h:52).
  PartitionChannel — shard fan-out by partition index; each partition is
                     its own server group (partition_channel.h:46-136).

These are host-side fan-outs over arbitrary transports. When every
sub-target is a device on one mesh, prefer parallel/collective.py which
lowers the same shape onto XLA collectives instead of N point-to-point
calls.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Sequence

from brpc_tpu.rpc import errno_codes as berr
from brpc_tpu.rpc.channel import Channel
from brpc_tpu.rpc.controller import Controller
from brpc_tpu.rpc.load_balancer import LoadBalancer, new_load_balancer
from brpc_tpu.butil.endpoint import EndPoint


class SubCall:
    """What a CallMapper returns for one sub-channel."""

    __slots__ = ("service", "method", "request", "device_arrays", "skip")

    def __init__(self, service: str, method: str, request: Any,
                 device_arrays: Optional[List] = None, skip: bool = False):
        self.service = service
        self.method = method
        self.request = request
        self.device_arrays = device_arrays
        self.skip = skip

    @classmethod
    def skipped(cls) -> "SubCall":
        return cls("", "", b"", skip=True)


class CallMapper:
    """Maps the logical call onto sub-channel i (parallel_channel.h:94)."""

    def map(self, sub_index: int, nsub: int, service: str, method: str,
            request: Any, cntl: Controller) -> SubCall:
        return SubCall(service, method, request,
                       device_arrays=cntl.request_device_arrays or None)


class ResponseMerger:
    """Folds one finished sub-call into the final controller
    (parallel_channel.h:127). Default: collect payload bytes in order."""

    def merge(self, final_cntl: Controller, sub_index: int,
              sub_cntl: Controller) -> None:
        final_cntl.sub_responses[sub_index] = (
            sub_cntl.response_payload.to_bytes()
            if sub_cntl.response_payload is not None else None)
        if sub_cntl.response_device_arrays:
            final_cntl.sub_device_arrays[sub_index] = \
                sub_cntl.response_device_arrays


class ParallelChannel:
    def __init__(self, fail_limit: Optional[int] = None,
                 call_mapper: Optional[CallMapper] = None,
                 response_merger: Optional[ResponseMerger] = None):
        self._subs: List[Channel] = []
        self.fail_limit = fail_limit
        self.call_mapper = call_mapper or CallMapper()
        self.response_merger = response_merger or ResponseMerger()
        # collective lowering (parallel/collective.py): when every
        # sub-channel rides the device lane of one mesh, the N-sub-call
        # fan-out is the wrong program — attach_collective swaps it for
        # ONE jit'd shard_map op per (service, method)
        self._collective = None
        self._collective_fns: Dict[Any, Callable] = {}
        self._lane_verdict: Optional[bool] = None
        self.collective_fused = 0
        self.collective_fallbacks = 0

    def attach_collective(self, collective,
                          service_fns: Dict[Any, Callable]) -> None:
        """Arm collective lowering: ``collective`` is a
        parallel.collective.CollectiveChannel over the mesh whose
        devices back the sub-channels; ``service_fns`` maps
        ``(service, method)`` to the jax-traceable per-shard function
        equivalent to what that RPC method computes. A device-array
        call to a mapped method then lowers to ONE XLA collective
        (scatter over the shard axis + on-device merge) instead of N
        point-to-point lane RPCs — the fan-out and merge become ICI
        traffic inside one compiled program. Calls that don't qualify
        (host payloads, unmapped methods, a non-device-lane sub) fan
        out exactly as before."""
        self._collective = collective
        self._collective_fns = dict(service_fns)
        self._lane_verdict = None

    def _all_device_lane(self) -> bool:
        """One probe per sub-channel generation: every sub must expose
        a device lane for the fused program to be equivalent (a plain
        TCP sub would silently drop out of a collective)."""
        if self._lane_verdict is None:
            try:
                self._lane_verdict = bool(self._subs) and all(
                    sub.device_lane_kind() is not None
                    for sub in self._subs)
            except Exception:
                self._lane_verdict = False
        return self._lane_verdict

    def _maybe_collective(self, service: str, method: str,
                          cntl: Controller) -> bool:
        """Try the fused path; True means the call completed there.
        Any lowering failure falls back to the per-sub fan-out — the
        optimization must never change call semantics."""
        coll = self._collective
        if coll is None:
            return False
        fn = self._collective_fns.get((service, method))
        if fn is None:
            return False
        arrs = cntl.request_device_arrays
        if not arrs or len(arrs) != 1:
            return False
        if type(self.call_mapper) is not CallMapper:
            # a custom mapper rewrites per-sub requests; the collective
            # can only express the stock scatter shape
            return False
        if len(self._subs) != coll.n_shards or not self._all_device_lane():
            return False
        try:
            out = coll.call(fn, arrs[0])
        except Exception:
            self.collective_fallbacks += 1
            return False
        self.collective_fused += 1
        cntl.collective_lowered = True
        cntl.response_device_arrays = [out]
        cntl._complete()
        return True

    def add_sub_channel(self, ch: Channel) -> None:
        self._subs.append(ch)
        self._lane_verdict = None

    @property
    def sub_channel_count(self) -> int:
        return len(self._subs)

    def call(self, service: str, method: str, request: Any = b"",
             cntl: Optional[Controller] = None,
             done: Optional[Callable] = None, **kw) -> Controller:
        cntl = cntl or Controller()
        cntl._done_cb = done
        # snapshot: DynamicPartitionChannel swaps self._subs atomically on
        # re-partition; one call must see one consistent generation
        subs = self._subs
        nsub = len(subs)
        cntl.sub_responses = [None] * nsub
        cntl.sub_device_arrays = [None] * nsub
        cntl.sub_errors = [None] * nsub
        if nsub == 0:
            cntl.set_failed(berr.EINTERNAL, "no sub channels")
            cntl._complete()
            return cntl
        if self._maybe_collective(service, method, cntl):
            return cntl
        fail_limit = (self.fail_limit if self.fail_limit is not None else nsub)
        state = {"pending": 0, "failed": 0, "done": False}
        lock = threading.Lock()
        sub_calls = []
        for i, sub in enumerate(subs):
            sc = self.call_mapper.map(i, nsub, service, method, request, cntl)
            if sc is None or sc.skip:
                continue
            sub_calls.append((i, sub, sc))
        if not sub_calls:
            cntl.set_failed(berr.EREQUEST, "call mapper skipped every sub call")
            cntl._complete()
            return cntl
        state["pending"] = len(sub_calls)

        def on_sub_done(i):
            def _cb(sub_cntl):
                finish = False
                with lock:
                    if state["done"]:
                        return
                    if sub_cntl.failed():
                        state["failed"] += 1
                        cntl.sub_errors[i] = (sub_cntl.error_code,
                                              sub_cntl.error_text)
                    state["pending"] -= 1
                    if state["failed"] >= fail_limit or state["pending"] == 0:
                        state["done"] = True
                        finish = True
                if not sub_cntl.failed():
                    try:
                        self.response_merger.merge(cntl, i, sub_cntl)
                    except Exception as e:
                        with lock:
                            state["failed"] += 1
                        cntl.sub_errors[i] = (berr.ERESPONSE,
                                              f"merger failed: {e}")
                if finish:
                    if state["failed"] >= fail_limit:
                        cntl.set_failed(
                            berr.ETOOMANYFAILS,
                            f"{state['failed']}/{len(sub_calls)} sub calls failed")
                    cntl._complete()
            return _cb

        for i, sub, sc in sub_calls:
            sub.call(sc.service, sc.method, sc.request,
                     done=on_sub_done(i),
                     request_device_arrays=sc.device_arrays, **kw)
        return cntl

    def call_sync(self, service, method, request=b"", timeout_s: float = 30.0,
                  **kw) -> Controller:
        cntl = self.call(service, method, request, **kw)
        cntl.join(timeout_s)
        return cntl


class SelectiveChannel:
    """Pick ONE healthy sub-channel per call; retries go to a different
    one (selective_channel.h:52)."""

    def __init__(self, load_balancer: str | LoadBalancer = "rr",
                 max_retry: int = 2):
        self._subs: List[Channel] = []
        self._lb = (load_balancer if isinstance(load_balancer, LoadBalancer)
                    else new_load_balancer(load_balancer))
        self.max_retry = max_retry

    def add_sub_channel(self, ch: Channel) -> None:
        self._subs.append(ch)
        # the LB keys sub-channels by synthetic endpoints (index as host)
        self._lb.reset_servers(
            tuple(EndPoint("sub", str(i), 0) for i in range(len(self._subs))))

    def call(self, service: str, method: str, request: Any = b"",
             cntl: Optional[Controller] = None,
             done: Optional[Callable] = None, **kw) -> Controller:
        cntl = cntl or Controller()
        cntl._done_cb = done
        tried: set = set()
        outer = self

        def attempt(tries_left: int):
            ep = outer._lb.select_server(tried or None)
            if ep is None:
                cntl.set_failed(berr.ETOOMANYFAILS, "no sub channel left")
                cntl._complete()
                return
            tried.add(ep)
            sub = outer._subs[int(ep.host)]

            def _cb(sub_cntl):
                outer._lb.feedback(ep, sub_cntl.latency_us(), sub_cntl.failed())
                if sub_cntl.failed() and tries_left > 0:
                    attempt(tries_left - 1)
                    return
                cntl.error_code = sub_cntl.error_code
                cntl.error_text = sub_cntl.error_text
                cntl.response_payload = sub_cntl.response_payload
                cntl.response_device_arrays = sub_cntl.response_device_arrays
                cntl.response_attachment = sub_cntl.response_attachment
                cntl._complete()

            sub.call(service, method, request, done=_cb, **kw)

        attempt(self.max_retry)
        return cntl

    def call_sync(self, service, method, request=b"", timeout_s: float = 30.0,
                  **kw) -> Controller:
        cntl = self.call(service, method, request, **kw)
        cntl.join(timeout_s)
        return cntl


class PartitionParser:
    """Splits a logical request into per-partition requests
    (partition_channel.h:46)."""

    def parse(self, partition_index: int, num_partitions: int, service: str,
              method: str, request: Any, cntl: Controller) -> SubCall:
        return SubCall(service, method, request)


class PartitionChannel(ParallelChannel):
    """Fan out one call to all partitions of a sharded service; partition
    i's servers come from sub-channel i (partition_channel.h:75)."""

    def __init__(self, partition_parser: Optional[PartitionParser] = None,
                 fail_limit: Optional[int] = 1,
                 response_merger: Optional[ResponseMerger] = None):
        parser = partition_parser or PartitionParser()
        outer_self = self

        class _Mapper(CallMapper):
            def map(self, i, nsub, service, method, request, cntl):
                return parser.parse(i, nsub, service, method, request, cntl)

        super().__init__(fail_limit=fail_limit, call_mapper=_Mapper(),
                         response_merger=response_merger)
        self.partition_parser = parser

    def add_partition(self, ch: Channel) -> None:
        self.add_sub_channel(ch)

    @property
    def partition_count(self) -> int:
        return self.sub_channel_count


class DynamicPartitionChannel(PartitionChannel):
    """PartitionChannel that re-shards as the naming service changes the
    partition map (partition_channel.h:136 DynamicPartitionChannel +
    the _dynpart LB). Server entries carry ``#partition=K/N``; on every
    update the sub-channel list is rebuilt to N partitions, each backed
    by a list:// cluster of that partition's replicas, and swapped in
    atomically (in-flight calls keep the generation they started with)."""

    def __init__(self, naming_url: str,
                 partition_parser: Optional[PartitionParser] = None,
                 fail_limit: Optional[int] = 1,
                 response_merger: Optional[ResponseMerger] = None,
                 options=None, control=None):
        super().__init__(partition_parser, fail_limit, response_merger)
        from brpc_tpu.rpc.naming import NamingServiceThread
        self._options = options
        self._control = control
        self._generation = 0
        self._ready = threading.Event()
        self._retired: List[list] = []
        self._retire_lock = threading.Lock()
        self._ns = NamingServiceThread(naming_url, control=control)
        self._ns.watch(self._rebuild)

    def wait_ready(self, timeout_s: float = 5.0) -> bool:
        return self._ready.wait(timeout_s)

    def _rebuild(self, servers) -> None:
        from brpc_tpu.rpc.cluster_channel import ClusterChannel
        by_partition: Dict[int, list] = {}
        nparts = 0
        for ep in servers:
            spec = ep.extra("partition")
            if not spec or "/" not in spec:
                continue
            k_s, n_s = spec.split("/", 1)
            try:
                k, n = int(k_s), int(n_s)
            except ValueError:
                continue
            if n <= 0 or not 0 <= k < n:
                continue
            nparts = max(nparts, n)
            by_partition.setdefault(k, []).append(
                EndPoint(ep.scheme, ep.host, ep.port))
        new_subs = []
        for k in range(nparts):
            eps = by_partition.get(k)
            if not eps:
                # a hole in the partition map: serve what we can; calls
                # hitting the missing shard fail via the empty cluster
                eps = []
            url = "list://" + ",".join(str(e) for e in eps)
            new_subs.append(ClusterChannel(url, "rr", self._options,
                                           control=self._control))
        old, self._subs = self._subs, new_subs   # atomic ref swap
        self._generation += 1
        self._ready.set()
        if old:
            # in-flight calls still hold the old generation: closing now
            # would fail their sub-calls mid-flight. Retire after a grace
            # period instead.
            from brpc_tpu.fiber.timer import global_timer
            with self._retire_lock:
                self._retired.append(old)
            global_timer().schedule_after(10.0, self._close_retired)

    def _close_retired(self) -> None:
        with self._retire_lock:
            gens, self._retired = self._retired[:1], self._retired[1:]
        for gen in gens:
            for ch in gen:
                try:
                    ch.close()
                except Exception:
                    pass

    def close(self) -> None:
        self._ns.stop()
        with self._retire_lock:
            gens, self._retired = self._retired, []
        for gen in gens + [self._subs]:
            for ch in gen:
                try:
                    ch.close()
                except Exception:
                    pass
