"""RPC error codes (brpc/errno.proto equivalents)."""

OK = 0
ENOSERVICE = 1001       # service not found
ENOMETHOD = 1002        # method not found
EREQUEST = 1003         # bad request
ERPCAUTH = 1004         # auth failed
ETOOMANYFAILS = 1005    # too many sub-channel failures (combo channels)
EBACKUPREQUEST = 1007   # backup request fired (internal)
ERPCTIMEDOUT = 1008     # RPC deadline exceeded
EFAILEDSOCKET = 1009    # connection broken during call
EHTTP = 1010            # HTTP-level error
EOVERCROWDED = 1011     # too many buffered writes / server concurrency full
EPERM = 1012            # rejected by server interceptor / permission
EINTERNAL = 2001        # server-side handler exception
ERESPONSE = 2002        # bad response
ELOGOFF = 2003          # server is stopping
ELIMIT = 2004           # concurrency limiter rejected
ECLOSE = 2005           # connection closed by peer
ECANCELED = 2006        # call canceled
ENAMINGEMPTY = 2007     # naming service resolved no servers (cluster
#                         channel fails fast instead of a generic pick
#                         failure — see /vars naming_empty)
EPRIORITYSHED = 2008    # DAGOR priority admission shed: the request's
#                         (business, user) level sat below the server's
#                         current admission threshold — a µs-cheap
#                         reject distinct from ELIMIT so operators see
#                         WHICH overload organ fired (rpc/admission.py)

_NAMES = {v: k for k, v in list(globals().items()) if isinstance(v, int)}


def errno_name(code: int) -> str:
    return _NAMES.get(code, f"E{code}")
