"""DeviceEventPoller: park fibers on device/async futures.

The north-star twist on the fork's RingListener/EloqModule design
(bthread/ring_listener.h:115, eloq_module.h:60): instead of an io_uring
CQE pump per worker group, one poller thread drains *device event*
completions — jax.Array readiness (`.is_ready()` over PjRt's future) and
concurrent.futures.Future — and reschedules the parked fiber into its
(possibly bound) group, so RPC handlers can launch XLA computations
without burning a worker thread on `block_until_ready`.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional, Tuple

from brpc_tpu.fiber.scheduler import Fiber, SchedAwaitable


def _is_ready(obj: Any) -> bool:
    ready_fn = getattr(obj, "is_ready", None)
    if ready_fn is not None:
        return bool(ready_fn())
    done_fn = getattr(obj, "done", None)  # concurrent.futures.Future
    if done_fn is not None:
        return bool(done_fn())
    return True


class DeviceEventPoller:
    """Single pump thread; adaptive spin-then-sleep polling."""

    def __init__(self, name: str = "device_poller"):
        self._cond = threading.Condition()
        self._pending: List[Tuple[Any, Callable[[], None]]] = []
        self._thread: Optional[threading.Thread] = None
        self._name = name
        self._stop = False

    def watch(self, obj: Any, on_ready: Callable[[], None]) -> None:
        """Call on_ready() once obj becomes ready. If a Future supports
        callbacks, use them directly (no polling)."""
        add_cb = getattr(obj, "add_done_callback", None)
        if add_cb is not None:
            add_cb(lambda _f: on_ready())
            return
        if _is_ready(obj):
            on_ready()
            return
        with self._cond:
            self._pending.append((obj, on_ready))
            self._ensure_thread()
            self._cond.notify()

    def _ensure_thread(self):
        if self._thread is None or not self._thread.is_alive():
            self._stop = False
            self._thread = threading.Thread(target=self._run, name=self._name,
                                            daemon=True)
            self._thread.start()

    def _run(self):
        import time
        idle_spins = 0
        while not self._stop:
            with self._cond:
                if not self._pending:
                    self._cond.wait(0.5)
                    continue
                pending = self._pending
                self._pending = []
            still = []
            fired = 0
            for obj, cb in pending:
                if _is_ready(obj):
                    fired += 1
                    try:
                        cb()
                    except Exception:
                        import logging
                        logging.getLogger("brpc_tpu.fiber").exception(
                            "device poller callback failed")
                else:
                    still.append((obj, cb))
            if still:
                with self._cond:
                    self._pending.extend(still)
            if fired:
                idle_spins = 0
            else:
                # adaptive backoff: spin a few rounds (device events complete
                # in µs), then sleep a little to spare the host
                idle_spins += 1
                if idle_spins > 64:
                    time.sleep(0.0002)

    def stop(self):
        self._stop = True
        with self._cond:
            self._cond.notify()


_global_poller: Optional[DeviceEventPoller] = None
_lock = threading.Lock()


def global_poller() -> DeviceEventPoller:
    global _global_poller
    if _global_poller is None:
        with _lock:
            if _global_poller is None:
                _global_poller = DeviceEventPoller()
    return _global_poller


def device_ready(obj: Any) -> SchedAwaitable:
    """Awaitable: park the fiber until a jax.Array / Future is ready, then
    resume with the object itself (its result for Futures)."""

    class _Ready(SchedAwaitable):
        def _register(self, fiber: Fiber):
            def on_ready():
                result = obj
                res_fn = getattr(obj, "result", None)
                if res_fn is not None and hasattr(obj, "done"):
                    try:
                        result = res_fn()
                    except Exception:
                        result = obj
                fiber.control.schedule(fiber, result)
            global_poller().watch(obj, on_ready)
    return _Ready()
