"""DeviceEventPoller: park fibers on device/async futures — event-driven.

The north-star twist on the fork's RingListener/EloqModule design
(bthread/ring_listener.h:115, eloq_module.h:60): instead of an io_uring
CQE pump per worker group, device completions wake fibers through real
blocking waits, not polling:

* concurrent.futures.Future → its own ``add_done_callback`` (zero cost);
* jax.Array (and anything with ``block_until_ready``) → a small pool of
  waiter threads each parks INSIDE PjRt's C++ future wait (the GIL is
  released), so the wake is the runtime's own completion signal — the
  io_uring CQE analog — with µs latency instead of a sleep-loop quantum;
* exotic objects with only ``is_ready()`` → the legacy spin-then-sleep
  pump, kept as a fallback.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional, Tuple

from brpc_tpu.fiber.scheduler import Fiber, SchedAwaitable
# device-thread labels for the flight recorder: the pump thread and the
# per-wait PjRt waiter threads run OUTSIDE any fiber, so without these
# stamps their busy samples fall to thread-name leaves instead of the
# device lane. Bound at module load (transport/__init__ is empty — no
# cycle; and the sampler side reads only, per the PR 8 lazy-import rule)
from brpc_tpu.transport.device_stats import (stamp_device_thread,
                                             unstamp_device_thread)

# cap on concurrently-parked waiter threads; beyond it new waits fall
# back to the fair poll pump (a bounded executor QUEUE would let 32
# stalled waits starve a ready one behind them)
_MAX_WAITERS = 128


def _is_ready(obj: Any) -> bool:
    ready_fn = getattr(obj, "is_ready", None)
    if ready_fn is not None:
        return bool(ready_fn())
    done_fn = getattr(obj, "done", None)  # concurrent.futures.Future
    if done_fn is not None:
        return bool(done_fn())
    return True


class DeviceEventPoller:
    """Event-driven waits with a polling fallback pump."""

    def __init__(self, name: str = "device_poller"):
        self._cond = threading.Condition()
        self._pending: List[Tuple[Any, Callable[[], None]]] = []
        self._thread: Optional[threading.Thread] = None
        self._name = name
        self._stop = False
        self._active_waiters = 0
        self._waiter_lock = threading.Lock()

    def watch(self, obj: Any, on_ready: Callable[[], None]) -> None:
        """Call on_ready() once obj becomes ready. Prefers real
        completion signals (done-callback / blocking C++ wait) over
        polling."""
        add_cb = getattr(obj, "add_done_callback", None)
        if add_cb is not None:
            add_cb(lambda _f: on_ready())
            return
        if _is_ready(obj):
            on_ready()
            return
        block = getattr(obj, "block_until_ready", None)
        if block is not None:
            with self._waiter_lock:
                can_wait = self._active_waiters < _MAX_WAITERS
                if can_wait:
                    self._active_waiters += 1
            if can_wait:
                def wait_and_fire():
                    stamp_device_thread("device:wait")
                    try:
                        block()       # parks in PjRt's future (GIL freed)
                    except Exception:
                        pass          # errors surface at use time
                    finally:
                        with self._waiter_lock:
                            self._active_waiters -= 1
                    try:
                        on_ready()
                    except Exception:
                        import logging
                        logging.getLogger("brpc_tpu.fiber").exception(
                            "device waiter callback failed")
                    finally:
                        # per-wait threads die here: an un-popped label
                        # would pin dict entries for dead tids
                        unstamp_device_thread()
                # one daemon thread per in-flight wait: a stalled wait
                # pins only its own thread (no executor queue to starve
                # ready objects behind it) and cannot hang interpreter
                # exit the way non-daemon pool threads would
                threading.Thread(target=wait_and_fire,
                                 name=f"{self._name}_wait",
                                 daemon=True).start()
                return
            # over the cap: fall through to the fair poll pump
        with self._cond:
            self._pending.append((obj, on_ready))
            self._ensure_thread()
            self._cond.notify()

    def _ensure_thread(self):
        if self._thread is None or not self._thread.is_alive():
            self._stop = False
            self._thread = threading.Thread(target=self._run, name=self._name,
                                            daemon=True)
            self._thread.start()

    def _run(self):
        import time
        # the pump's busy samples (is_ready sweeps over pending device
        # objects) belong to the device lane on /hotspots; the unstamp
        # rides a finally — a pump killed by a throwing is_ready must
        # not leave a stale label for the OS to hand a reused tid
        stamp_device_thread(f"device:{self._name}")
        try:
            self._run_inner(time)
        finally:
            unstamp_device_thread()

    def _run_inner(self, time):
        idle_spins = 0
        while not self._stop:
            with self._cond:
                if not self._pending:
                    self._cond.wait(0.5)
                    continue
                pending = self._pending
                self._pending = []
            still = []
            fired = 0
            for obj, cb in pending:
                if _is_ready(obj):
                    fired += 1
                    try:
                        cb()
                    except Exception:
                        import logging
                        logging.getLogger("brpc_tpu.fiber").exception(
                            "device poller callback failed")
                else:
                    still.append((obj, cb))
            if still:
                with self._cond:
                    self._pending.extend(still)
            if fired:
                idle_spins = 0
            else:
                # adaptive backoff: spin a few rounds (device events complete
                # in µs), then sleep a little to spare the host
                idle_spins += 1
                if idle_spins > 64:
                    # graftlint: disable=event-wait-not-sleep -- 200µs
                    # adaptive backoff between device-event poll spins:
                    # stop() is a _cond notify away and a 200µs tail is
                    # noise; an Event.wait at this period would only add
                    # lock traffic to the µs-scale completion path
                    time.sleep(0.0002)

    def stop(self):
        self._stop = True
        with self._cond:
            self._cond.notify()


_global_poller: Optional[DeviceEventPoller] = None
_lock = threading.Lock()


def global_poller() -> DeviceEventPoller:
    global _global_poller
    if _global_poller is None:
        with _lock:
            if _global_poller is None:
                _global_poller = DeviceEventPoller()
    return _global_poller


def _postfork_reset() -> None:
    """Fork hygiene: the poller thread and its parked fibers belong to
    the parent's scheduler; a fresh child polls nothing yet."""
    global _global_poller, _lock
    _global_poller = None
    _lock = threading.Lock()


from brpc_tpu.butil import postfork  # noqa: E402  (registration ships
#                                      with the singleton it resets)

postfork.register("fiber.device_poller", _postfork_reset)


def device_ready(obj: Any) -> SchedAwaitable:
    """Awaitable: park the fiber until a jax.Array / Future is ready, then
    resume with the object itself (its result for Futures)."""

    class _Ready(SchedAwaitable):
        def _register(self, fiber: Fiber):
            def on_ready():
                result = obj
                res_fn = getattr(obj, "result", None)
                if res_fn is not None and hasattr(obj, "done"):
                    try:
                        result = res_fn()
                    except Exception:
                        result = obj
                fiber.control.schedule(fiber, result)
            global_poller().watch(obj, on_ready)
    return _Ready()
