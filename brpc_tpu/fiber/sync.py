"""Blocking primitives built on Butex, mirroring bthread's mutex /
condition_variable / countdown_event (all butex-based in the reference).

Every primitive is dual-mode: awaitable from fibers, blocking from plain
threads — the same duality bthread keeps (butex serves both waiter kinds).
"""

from __future__ import annotations

import threading
from typing import Optional

from brpc_tpu.fiber.butex import WAIT_OK, WAIT_TIMEOUT, Butex
from brpc_tpu.fiber.scheduler import current_fiber


class FiberMutex:
    """bthread_mutex: butex-based; never blocks the worker thread when
    contended from a fiber (the fiber suspends instead).

    Contended acquisitions are sampled into the contention profiler
    (the reference hooks the same way inside bthread/mutex.cpp —
    bounded by the collector's per-second budget, so the hot path pays
    one CAS when uncontended and one submit attempt when contended)."""

    def __init__(self):
        self._butex = Butex(0)  # 0 = unlocked, 1 = locked

    async def lock(self):
        if self._butex.compare_exchange(0, 1):
            return
        from brpc_tpu.fiber.contention import record_contention
        import time
        t0 = time.monotonic_ns()
        while not self._butex.compare_exchange(0, 1):
            await self._butex.wait(expected=1)
        record_contention(self, (time.monotonic_ns() - t0) / 1e3)

    def unlock(self):
        self._butex.set_value(0)
        self._butex.wake(1)

    def lock_pthread(self, timeout_s: Optional[float] = None) -> bool:
        import time
        if self._butex.compare_exchange(0, 1):
            return True
        deadline = None if timeout_s is None else time.monotonic() + timeout_s
        t0 = time.monotonic_ns()
        while not self._butex.compare_exchange(0, 1):
            remain = None if deadline is None else deadline - time.monotonic()
            if remain is not None and remain <= 0:
                return False
            self._butex.wait_pthread(expected=1, timeout_s=remain)
        from brpc_tpu.fiber.contention import record_contention
        record_contention(self, (time.monotonic_ns() - t0) / 1e3)
        return True

    async def __aenter__(self):
        await self.lock()
        return self

    async def __aexit__(self, *exc):
        self.unlock()
        return False


class FiberEvent:
    """One-shot event (set stays set)."""

    def __init__(self):
        self._butex = Butex(0)

    def is_set(self) -> bool:
        return self._butex.value == 1

    def set(self):
        self._butex.set_and_wake_all(1)

    async def wait(self, timeout_s: Optional[float] = None) -> bool:
        if self._butex.value == 1:
            return True
        res = await self._butex.wait(expected=0, timeout_s=timeout_s)
        return res != WAIT_TIMEOUT or self._butex.value == 1

    def wait_pthread(self, timeout_s: Optional[float] = None) -> bool:
        if self._butex.value == 1:
            return True
        res = self._butex.wait_pthread(expected=0, timeout_s=timeout_s)
        return res != WAIT_TIMEOUT or self._butex.value == 1


class CountdownEvent:
    """bthread::CountdownEvent — the fan-out joiner ParallelChannel uses."""

    def __init__(self, count: int = 1):
        self._butex = Butex(count)

    def signal(self, n: int = 1):
        with self._butex._lock:
            self._butex._value = max(0, self._butex._value - n)
            done = self._butex._value == 0
        if done:
            # wake only at zero: waiters parked on a nonzero count stay
            # parked (their add_waiter re-checked the value at registration,
            # so no intermediate decrement can be missed)
            self._butex.wake_all()

    def add_count(self, n: int = 1):
        self._butex.fetch_add(n)

    @property
    def count(self) -> int:
        return self._butex.value

    async def wait(self, timeout_s: Optional[float] = None) -> bool:
        import time
        deadline = None if timeout_s is None else time.monotonic() + timeout_s
        while True:
            v = self._butex.value
            if v == 0:
                return True
            remain = None if deadline is None else deadline - time.monotonic()
            if remain is not None and remain <= 0:
                return False
            res = await self._butex.wait(expected=v, timeout_s=remain)
            if res == WAIT_TIMEOUT:
                return self._butex.value == 0

    def wait_pthread(self, timeout_s: Optional[float] = None) -> bool:
        import time
        deadline = None if timeout_s is None else time.monotonic() + timeout_s
        while True:
            v = self._butex.value
            if v == 0:
                return True
            remain = None if deadline is None else deadline - time.monotonic()
            if remain is not None and remain <= 0:
                return False
            self._butex.wait_pthread(expected=v, timeout_s=remain)


class FiberCondition:
    """Condition variable over FiberMutex (bthread_cond)."""

    def __init__(self, mutex: FiberMutex):
        self._mutex = mutex
        self._butex = Butex(0)

    async def wait(self, timeout_s: Optional[float] = None) -> bool:
        seq = self._butex.value
        self._mutex.unlock()
        res = await self._butex.wait(expected=seq, timeout_s=timeout_s)
        await self._mutex.lock()
        return res != WAIT_TIMEOUT

    def notify(self, n: int = 1):
        self._butex.fetch_add(1)
        self._butex.wake(n)

    def notify_all(self):
        self._butex.fetch_add(1)
        self._butex.wake_all()
