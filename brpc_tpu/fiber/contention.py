"""Contention profiler: samples FiberMutex lock-wait events through a
budgeted Collector (the reference's contention profiler lives inside
bthread/mutex.cpp and renders at /hotspots; here /contentions).

Each admitted sample records (site, wait_us) where site is the caller
frame that requested the lock — aggregation by site shows which lock
acquisition points hurt."""

from __future__ import annotations

import sys
from collections import defaultdict
from typing import Dict, List, NamedTuple, Tuple

from brpc_tpu.butil.flags import define_flag, flag
from brpc_tpu.bvar.collector import Collector

define_flag("contention_profiler_enabled", True,
            "sample FiberMutex contention events")
define_flag("contention_samples_per_second", 200,
            "budget for contention sampling")


class ContentionSample(NamedTuple):
    site: str
    wait_us: float


global_contention_collector = Collector(200, name="contention")


def _postfork_reset() -> None:
    """Fork hygiene: the collected samples describe PARENT-side lock
    waits and the budget lock may have been held by a dead thread at
    fork time — a shard starts with a clean contention profile."""
    import threading
    global_contention_collector._lock = threading.Lock()
    global_contention_collector._ring.clear()
    global_contention_collector._window_used = 0


from brpc_tpu.butil import postfork as _postfork  # noqa: E402
#   (registration ships with the collector it resets)

_postfork.register("fiber.contention", _postfork_reset)


def record_contention(mutex, wait_us: float) -> None:
    if not flag("contention_profiler_enabled"):
        return
    rate = flag("contention_samples_per_second")
    if global_contention_collector._rate != rate:
        global_contention_collector.set_rate(rate)
    # caller site: frame(0)=here, frame(1)=lock/lock_pthread, frame(2)=user
    try:
        frame = sys._getframe(2)
    except ValueError:
        frame = sys._getframe(1)
    code = frame.f_code
    site = f"{code.co_name} ({code.co_filename.rsplit('/', 1)[-1]}:{frame.f_lineno})"
    global_contention_collector.submit(ContentionSample(site, wait_us))


def contention_report(top: int = 30) -> List[Tuple[str, int, float]]:
    """[(site, count, total_wait_us)] sorted by total wait."""
    agg: Dict[str, List[float]] = defaultdict(list)
    for s in global_contention_collector.snapshot():
        agg[s.site].append(s.wait_us)
    rows = [(site, len(waits), sum(waits)) for site, waits in agg.items()]
    rows.sort(key=lambda r: -r[2])
    return rows[:top]
