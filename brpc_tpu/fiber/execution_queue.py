"""ExecutionQueue: MPSC serialized executor (bthread/execution_queue.h).

Producers push lock-free-ish (GIL-atomic deque append + one flag CAS); a
single drainer fiber consumes batches through the user's executor callback.
Exactly one drainer runs at a time — the property StreamingRPC's ordered
write path and LB feedback depend on.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Callable, Iterable, Optional

from brpc_tpu.fiber.scheduler import TaskControl, global_control

STOP_TASK = object()


class ExecutionQueue:
    def __init__(self, execute: Callable[[Iterable[Any]], Any],
                 control: Optional[TaskControl] = None, name: str = "execq"):
        """``execute(tasks)`` receives an iterable batch, called from a
        fiber; it may be sync or async."""
        self._execute = execute
        self._control = control
        self._q: deque = deque()
        self._flag_lock = threading.Lock()
        self._draining = False
        self._stopped = False
        self._name = name
        self._idle = threading.Event()
        self._idle.set()

    def execute(self, task: Any) -> bool:
        """Push a task; returns False if the queue is stopped."""
        if self._stopped:
            return False
        self._q.append(task)
        self._maybe_start_drainer()
        return True

    def _maybe_start_drainer(self):
        with self._flag_lock:
            if self._draining or not self._q:
                return
            self._draining = True
            self._idle.clear()
        ctrl = self._control or global_control()
        ctrl.spawn(self._drain, name=self._name)

    async def _drain(self):
        import inspect
        while True:
            batch = []
            while True:
                try:
                    batch.append(self._q.popleft())
                except IndexError:
                    break
            if batch:
                r = self._execute(batch)
                if inspect.iscoroutine(r):
                    await r
            with self._flag_lock:
                if not self._q:
                    self._draining = False
                    self._idle.set()
                    return

    def stop(self):
        self._stopped = True

    def join(self, timeout: Optional[float] = None) -> bool:
        """Wait (from a plain thread) until the queue is fully drained."""
        return self._idle.wait(timeout)
