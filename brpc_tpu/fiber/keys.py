"""Fiber-local storage (bthread keys, bthread/key.cpp): values scoped to a
fiber's lifetime with optional destructors run at fiber exit."""

from __future__ import annotations

import itertools
from typing import Any, Callable, Optional

from brpc_tpu.fiber.scheduler import current_fiber

_key_seq = itertools.count()


class FiberLocal:
    """One key; get/set operate on the *current fiber*. Outside a fiber,
    falls back to a thread-level slot (like pthread-keys fallback)."""

    def __init__(self, destructor: Optional[Callable[[Any], None]] = None):
        self._id = ("fiber_local", next(_key_seq))
        self._destructor = destructor
        import threading
        self._thread_fallback = threading.local()

    def get(self, default: Any = None) -> Any:
        f = current_fiber()
        if f is None:
            return getattr(self._thread_fallback, "value", default)
        return f.locals.get(self._id, default)

    def peek(self, fiber, default: Any = None) -> Any:
        """Read ANOTHER fiber's slot (no thread fallback) — the
        flight-recorder sampler uses this to attribute a worker
        thread's sample to the RPC the fiber on it is serving. Racy by
        contract: dict reads are GIL-atomic, staleness is acceptable."""
        return fiber.locals.get(self._id, default)

    def set(self, value: Any) -> None:
        f = current_fiber()
        if f is None:
            self._thread_fallback.value = value
            return
        if self._destructor is not None and self._id not in f.locals:
            key_id = self._id
            dtor = self._destructor

            def _run_dtor(fiber):
                if key_id in fiber.locals:
                    dtor(fiber.locals[key_id])
            f._key_destructors.append(_run_dtor)
        f.locals[self._id] = value
