"""M:N fiber scheduler: work-stealing worker threads stepping coroutines.

TPU-native re-design of the reference's bthread runtime (SURVEY.md §2.2):

  TaskControl (task_control.h:42)  -> TaskControl: owns N workers + parking
  TaskGroup   (task_group.h:70)    -> TaskGroup: per-worker run queues
  WorkStealingQueue                -> collections.deque (owner pops right /
                                      thieves pop left; GIL-atomic)
  ParkingLot  (parking_lot.h:31)   -> condition variable + signal counter
  fcontext asm switch              -> coroutine send/StopIteration stepping
  _bound_rq (fork's group-bound    -> Fiber.bound_group pinning, the hook
   bthreads, task_group.h:230)        TPU device affinity hangs off

A *fiber* wraps a Python coroutine. Workers pop a fiber and ``step`` it:
one ``coro.send`` advances it until it either finishes (StopIteration) or
awaits a scheduler token (a ``SchedAwaitable``), which re-registers the
fiber with whatever will wake it (butex, timer, device poller, io).
Plain callables are wrapped in a trivial coroutine; they may block their
worker thread (the reference's usercode_in_pthread escape hatch).

Unlike bthread's start_urgent, a running Python frame can't be preempted,
so ``spawn_urgent`` pushes to the *head* of the local queue instead
(runs at the next suspension point).
"""

from __future__ import annotations

import inspect
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Deque, List, Optional

from brpc_tpu.butil.fast_rand import fast_rand_less_than
from brpc_tpu.bvar.reducer import Adder, Maxer, PassiveStatus

_wake_rec = None
_wake_rec_lock = threading.Lock()


def _wake_recorder():
    """LatencyRecorder for wake-to-run latency, exposed lazily as
    fiber_wake (the import is deferred to dodge the bvar->fiber
    circular import at module load). Re-exposes if the registry was
    cleared (bvar's unexpose_all test helper) — this recorder is a
    process-global singleton, so a dropped exposure would otherwise be
    permanent."""
    global _wake_rec
    if _wake_rec is None:
        with _wake_rec_lock:
            if _wake_rec is None:
                from brpc_tpu.bvar.latency_recorder import LatencyRecorder
                _wake_rec = LatencyRecorder().expose("fiber_wake")
    if getattr(_wake_rec, "_name", None) != "fiber_wake":
        _wake_rec.expose("fiber_wake")
    return _wake_rec

FIBER_STATE_READY = 0
FIBER_STATE_RUNNING = 1
FIBER_STATE_SUSPENDED = 2
FIBER_STATE_DONE = 3


class SchedAwaitable:
    """Base of everything a fiber may ``await``. ``_register(fiber)`` must
    arrange a future ``TaskControl.schedule(fiber, value)`` exactly once."""

    def _register(self, fiber: "Fiber") -> None:
        raise NotImplementedError

    def __await__(self):
        result = yield self
        return result


class _YieldNow(SchedAwaitable):
    def _register(self, fiber: "Fiber") -> None:
        fiber.control.schedule(fiber, None, to_tail=True)


def yield_now() -> SchedAwaitable:
    """Cooperatively reschedule (bthread_yield)."""
    return _YieldNow()


class Fiber:
    """One unit of M:N execution (bthread's TaskMeta)."""

    __slots__ = (
        "coro", "control", "state", "result", "exception", "bound_group",
        "locals", "_done_event", "_joiner_butex", "_resume_value", "name",
        "_key_destructors", "_ready_ns",
    )

    def __init__(self, coro, control: "TaskControl", name: str = ""):
        self.coro = coro
        self.control = control
        self.state = FIBER_STATE_READY
        self.result: Any = None
        self.exception: Optional[BaseException] = None
        self.bound_group: Optional[int] = None
        self.locals: dict = {}
        self.name = name
        self._done_event = None    # lazily created on first join()
        self._joiner_butex = None  # lazily created Butex for fiber joiners
        self._resume_value: Any = None
        self._key_destructors: List[Callable] = []
        self._ready_ns = 0

    # ---------------------------------------------------------------- join
    def done(self) -> bool:
        return self.state == FIBER_STATE_DONE

    def join(self, timeout: Optional[float] = None) -> bool:
        """Block the calling *thread* until the fiber finishes. Safe from
        non-fiber threads; inside a fiber prefer ``await fiber.join_async()``."""
        if self.state == FIBER_STATE_DONE:
            return True
        # the done Event is lazy (most fibers are never thread-joined):
        # create under the lock and re-check, so a _finish racing this
        # join either sees the event or already published DONE
        with _joiner_init_lock:
            if self.state == FIBER_STATE_DONE:
                return True
            ev = self._done_event
            if ev is None:
                ev = self._done_event = threading.Event()
        return ev.wait(timeout)

    def join_async(self) -> SchedAwaitable:
        """Awaitable join for use inside another fiber."""
        from brpc_tpu.fiber.butex import Butex
        if self._joiner_butex is None:
            with _joiner_init_lock:
                if self._joiner_butex is None:
                    self._joiner_butex = Butex(0)
        butex = self._joiner_butex

        class _Join(SchedAwaitable):
            def _register(_self, fiber):
                if self.done():
                    fiber.control.schedule(fiber, None)
                else:
                    butex.add_waiter(fiber, expected=0)
        return _Join()

    def value(self) -> Any:
        if self.exception is not None:
            raise self.exception
        return self.result

    def _finish(self, result, exc) -> None:
        self.result = result
        self.exception = exc
        for d in self._key_destructors:
            try:
                d(self)
            except Exception:
                pass
        self.state = FIBER_STATE_DONE
        if self._joiner_butex is not None:
            self._joiner_butex.set_and_wake_all(1)
        # pair with join()'s lazy creation: after DONE is published, any
        # event a joiner managed to install must still be set
        ev = self._done_event
        if ev is None:
            with _joiner_init_lock:
                ev = self._done_event
        if ev is not None:
            ev.set()
        self.control.nfibers.add(-1)
        if exc is not None and not isinstance(exc, SystemExit):
            self.control.on_fiber_error(self, exc)


_joiner_init_lock = threading.Lock()


class _CurrentCell:
    """Per-thread mutable holder of the fiber being stepped. A PLAIN
    object (not thread-local storage) registered by thread ident, so
    the flight-recorder sampler can read any thread's current fiber
    from outside — attributing a stack sample to the RPC method that
    fiber is serving. Reads are racy by design: a torn read costs one
    misattributed sample, never a crash."""

    __slots__ = ("current",)

    def __init__(self):
        self.current: Optional[Fiber] = None


class _WorkerTLS(threading.local):
    def __init__(self):
        self.group: Optional["TaskGroup"] = None
        self.inline_depth: int = 0
        # threading.local runs __init__ on each thread's FIRST attribute
        # touch, on that thread — so this registration executes exactly
        # once per thread, keyed by its own ident
        self.cell = _CurrentCell()
        _cell_by_thread[threading.get_ident()] = self.cell


# thread ident -> that thread's _CurrentCell (see _WorkerTLS.__init__);
# entries of dead threads are pruned by the sampler against the live
# tid set from sys._current_frames()
_cell_by_thread: dict = {}


_tls = _WorkerTLS()


def current_fiber() -> Optional[Fiber]:
    return _tls.cell.current


def thread_current_fiber(tid: int) -> Optional[Fiber]:
    """The fiber currently being stepped on thread ``tid`` (racy
    snapshot for samplers/watchdogs), or None for non-fiber threads and
    threads between steps."""
    cell = _cell_by_thread.get(tid)
    return cell.current if cell is not None else None


_prune_suspects: set = set()


def prune_thread_registry(live_tids) -> None:
    """Drop cells of dead threads (sampler housekeeping). TWO-strike:
    a cell is only removed when its thread was absent from two
    CONSECUTIVE live snapshots — a brand-new thread can register its
    cell between the sampler's frames snapshot and this prune, and a
    one-shot prune would delete it forever (threading.local.__init__
    never reruns, so the cell could not come back)."""
    global _prune_suspects
    # snapshot: another thread's FIRST _tls touch inserts mid-iteration
    gone = {tid for tid in list(_cell_by_thread) if tid not in live_tids}
    for tid in gone & _prune_suspects:
        _cell_by_thread.pop(tid, None)
    _prune_suspects = gone


def current_group() -> Optional["TaskGroup"]:
    return _tls.group


class ParkingLot:
    """Futex-style idle-worker parking (bthread/parking_lot.h:31)."""

    def __init__(self):
        self._cond = threading.Condition()
        self._signals = 0

    def signal_count(self) -> int:
        return self._signals

    def signal(self, n: int = 1) -> None:
        with self._cond:
            self._signals += 1
            self._cond.notify(n)

    def wait(self, expected: int, timeout: float = 1.0) -> None:
        with self._cond:
            if self._signals == expected:
                self._cond.wait(timeout)


class TaskGroup:
    """Per-worker scheduler state (bthread/task_group.h:70)."""

    def __init__(self, control: "TaskControl", index: int):
        self.control = control
        self.index = index
        self.rq: Deque[Fiber] = deque()         # local queue: owner pops right
        self.remote_rq: Deque[Fiber] = deque()  # pushed by non-workers
        self.bound_rq: Deque[Fiber] = deque()   # group-pinned fibers (fork's _bound_rq)
        self.nsteals = 0
        self.nswitches = 0
        self.nwakes = 0

    # owner-side pop order: bound first (pinned work can't run elsewhere),
    # then local LIFO for cache locality, then remote FIFO
    def pop_local(self) -> Optional[Fiber]:
        try:
            return self.bound_rq.popleft()
        except IndexError:
            pass
        try:
            return self.rq.pop()
        except IndexError:
            pass
        try:
            return self.remote_rq.popleft()
        except IndexError:
            return None

    def steal_from(self) -> Optional[Fiber]:
        """Thieves take the oldest local/remote task; bound tasks are never
        stolen."""
        try:
            return self.rq.popleft()
        except IndexError:
            pass
        try:
            return self.remote_rq.popleft()
        except IndexError:
            return None


class TaskControl:
    """Owns the worker pthreads (bthread/task_control.h:42)."""

    def __init__(self, concurrency: Optional[int] = None, name: str = "fiber"):
        if concurrency is None:
            # like bthread's default (8+1 workers even on small hosts):
            # fibers may run blocking user code, so a floor of spare workers
            # matters more than matching core count under the GIL
            concurrency = max(8, os.cpu_count() or 0)
        self.name = name
        self.concurrency = concurrency
        self.groups: List[TaskGroup] = [TaskGroup(self, i) for i in range(concurrency)]
        self.parking_lot = ParkingLot()
        self._threads: List[threading.Thread] = []
        self._stop = False
        self.nfibers = Adder(0)
        self.nfibers_created = Adder(0)
        # saturation instrumentation (the scheduler half of the rpcz
        # timeline story: when spans show queue_us growing, these name
        # the culprit). busy_ns accumulates worker time spent stepping
        # fibers — windowed into a busy fraction; runq_peak records the
        # deepest run queue seen at schedule() time — windowed into a
        # per-interval high-water mark.
        self.busy_ns = Adder(0)
        self.runq_peak = Maxer()
        self._busy_window = None       # PerSecond, created on first use
        self._runq_peak_window = None  # Window, created on first use
        self._error_handlers: List[Callable] = []
        self._started = False
        self._start_lock = threading.Lock()

    # -------------------------------------------------------------- start
    def start(self) -> None:
        with self._start_lock:
            if self._started:
                return
            self._started = True
            for g in self.groups:
                t = threading.Thread(target=self._worker, args=(g,),
                                     name=f"{self.name}_w{g.index}", daemon=True)
                self._threads.append(t)
                t.start()

    def stop_and_join(self, timeout: float = 5.0) -> None:
        self._stop = True
        with self._start_lock:
            # claim the pool under the same lock start() publishes it
            # with; _started stays True through the join so a racing
            # start() keeps no-opping instead of spawning a doomed
            # pool that would only see _stop and exit
            threads = list(self._threads)
            self._threads.clear()
        for _ in threads:
            self.parking_lot.signal(len(threads))
        for t in threads:
            t.join(timeout)
        with self._start_lock:
            # both flags flip in one critical section: dropping
            # _started with _stop still True would let a racing
            # start() spawn workers that instantly see _stop and
            # exit — a pool that claims started with nothing alive
            self._started = False
            self._stop = False

    # -------------------------------------------------------------- spawn
    def spawn(self, fn: Callable | Any, *args, name: str = "", urgent: bool = False,
              bound_group: Optional[int] = None, **kwargs) -> Fiber:
        """Start a fiber from a coroutine function, coroutine object, or
        plain callable (bthread_start_background / start_urgent)."""
        if inspect.iscoroutine(fn):
            coro = fn
        elif inspect.iscoroutinefunction(fn):
            coro = fn(*args, **kwargs)
        else:
            async def _runner():
                r = fn(*args, **kwargs)
                if inspect.isawaitable(r):
                    r = await r
                return r
            coro = _runner()
        fiber = Fiber(coro, self, name=name)
        if bound_group is not None:
            fiber.bound_group = bound_group % self.concurrency
        self.nfibers.add(1)
        self.nfibers_created.add(1)
        if not self._started:
            self.start()
        # note: the local queue is LIFO for the owner (Chase-Lev bottom), so a
        # plain push already runs next — bthread_start_urgent's "run NOW with
        # caller requeued" can't preempt a Python frame, and `urgent` adds
        # nothing beyond the LIFO push; it is accepted for API parity only
        self.schedule(fiber, None)
        return fiber

    def spawn_many(self, works, name: str = "") -> List[Fiber]:
        """Batch spawn with ONE parking-lot signal for the whole run —
        the amortized wake of a pipelined burst spill (N messages
        fanned out used to pay N condvar signals from the dispatcher
        thread, the per-burst scheduler cost the batched frame
        pipeline exists to remove). Semantics match N spawn() calls
        in submission order; accepts coroutines, coroutine functions
        and plain callables like spawn."""
        fibers: List[Fiber] = []
        if not works:
            return fibers
        if not self._started:
            self.start()
        g = _tls.group
        local = g is not None and g.control is self
        tgt = g if local else self.groups[
            fast_rand_less_than(self.concurrency)]
        for fn in works:
            if inspect.iscoroutine(fn):
                coro = fn
            elif inspect.iscoroutinefunction(fn):
                coro = fn()
            else:
                async def _runner(fn=fn):
                    r = fn()
                    if inspect.isawaitable(r):
                        r = await r
                    return r
                coro = _runner()
            fiber = Fiber(coro, self, name=name)
            self.nfibers.add(1)
            self.nfibers_created.add(1)
            fiber._ready_ns = time.perf_counter_ns()
            fiber.state = FIBER_STATE_READY
            if local:
                tgt.rq.append(fiber)       # owner-LIFO, like schedule()
            else:
                tgt.remote_rq.append(fiber)
            fibers.append(fiber)
        self.runq_peak.update(
            len(tgt.rq) + len(tgt.remote_rq) + len(tgt.bound_rq))
        self.parking_lot.signal(len(fibers))
        return fibers

    def run_inline(self, fn: Callable | Any, *args, name: str = "",
                   max_depth: int = 8, **kwargs) -> Fiber:
        """Step a new fiber on the CALLING thread until it completes or
        first suspends — the reference's process-in-place discipline
        (input_messenger.cpp:183 runs the last message in the receiving
        context) generalized: a handler chain that never blocks pays
        zero fiber wakes and zero cross-thread handoffs. On the first
        real suspension the remainder parks exactly like a spawned
        fiber (the awaitable registers it for a normal wake).

        ``max_depth`` bounds same-thread nesting (an inline handler
        whose write triggers the peer's inline processing recurses on
        this stack); past the cap we fall back to spawn."""
        depth = _tls.inline_depth
        if depth >= max_depth:
            return self.spawn(fn, *args, name=name, **kwargs)
        if inspect.iscoroutine(fn):
            coro = fn
        elif inspect.iscoroutinefunction(fn):
            coro = fn(*args, **kwargs)
        else:
            return self.spawn(fn, *args, name=name, **kwargs)
        fiber = Fiber(coro, self, name=name)
        self.nfibers.add(1)
        self.nfibers_created.add(1)
        if not self._started:
            # a suspension hands the continuation to the workers
            self.start()
        group = _tls.group or self.groups[0]
        _tls.inline_depth = depth + 1
        try:
            self._step(group, fiber)
        finally:
            _tls.inline_depth = depth
        return fiber

    def schedule(self, fiber: Fiber, resume_value: Any, to_tail: bool = False) -> None:
        """Make a ready fiber runnable (ready_to_run / ready_to_run_remote)."""
        fiber._resume_value = resume_value
        fiber._ready_ns = time.perf_counter_ns()
        fiber.state = FIBER_STATE_READY
        if fiber.bound_group is not None:
            g = self.groups[fiber.bound_group]
            g.bound_rq.append(fiber)
            self.runq_peak.update(
                len(g.rq) + len(g.remote_rq) + len(g.bound_rq))
            self.parking_lot.signal(1)
            return
        g = _tls.group
        if g is not None and g.control is self:
            if to_tail:
                g.rq.appendleft(fiber)    # back of the owner's LIFO
            else:
                g.rq.append(fiber)        # Chase-Lev bottom: owner runs it next
        else:
            # remote push: spread by random target group
            g = self.groups[fast_rand_less_than(self.concurrency)]
            g.remote_rq.append(fiber)
        # saturation high-water mark: the depth of the queue this fiber
        # just joined (cheap: three lens + a thread-local max update)
        self.runq_peak.update(
            len(g.rq) + len(g.remote_rq) + len(g.bound_rq))
        self.parking_lot.signal(1)

    # ------------------------------------------------------------- worker
    def _worker(self, group: TaskGroup) -> None:
        from brpc_tpu.fiber import worker_module
        _tls.group = group
        worker_module.notify_start(group.index)
        while not self._stop:
            # co-scheduled engine work first (the fork's EloqModule hook:
            # TaskGroup::ProcessModulesTask runs before wait_task pops)
            ran_module = worker_module.process_modules(group.index) \
                if worker_module.has_modules() else False
            fiber = group.pop_local()
            if fiber is None:
                fiber = self._steal(group)
            if fiber is not None:
                self._step(group, fiber)
                continue
            if ran_module:
                continue          # engine made progress: don't park yet
            expected = self.parking_lot.signal_count()
            # re-check after reading the signal count (no lost wakeups)
            fiber = group.pop_local() or self._steal(group)
            if fiber is not None:
                self._step(group, fiber)
                continue
            self.parking_lot.wait(expected, timeout=0.5)
        worker_module.notify_stop(group.index)
        _tls.group = None

    def _steal(self, group: TaskGroup) -> Optional[Fiber]:
        n = self.concurrency
        offset = fast_rand_less_than(n)
        for i in range(n):
            g = self.groups[(offset + i) % n]
            if g is group:
                continue
            f = g.steal_from()
            if f is not None:
                group.nsteals += 1
                return f
        return None

    def _step(self, group: TaskGroup, fiber: Fiber) -> None:
        """Advance the fiber one leg: run until it finishes or awaits."""
        cell = _tls.cell
        prev = cell.current
        cell.current = fiber
        fiber.state = FIBER_STATE_RUNNING
        ready_ns = fiber._ready_ns
        group.nswitches += 1
        if ready_ns:
            # wake-to-run latency: schedule() -> this step (the p99 the
            # event-driven wake path is accountable for; /vars
            # fiber_wake). Sampled 1-in-16 WAKES per group — counting
            # wakes, not switches, so the sample can't systematically
            # miss (a switch-indexed sample only fired when the 16th
            # switch happened to be a wake), and the FIRST wake records
            # so the recorder is visible as soon as any fiber ran.
            group.nwakes += 1
            if (group.nwakes & 0xF) == 1:
                _wake_recorder().record(
                    (time.perf_counter_ns() - ready_ns) / 1e3)
        fiber._ready_ns = 0
        t0 = time.perf_counter_ns()
        try:
            token = fiber.coro.send(fiber._resume_value)
        except StopIteration as e:
            self.busy_ns.add(time.perf_counter_ns() - t0)
            cell.current = prev
            fiber._finish(e.value, None)
            return
        except BaseException as e:
            self.busy_ns.add(time.perf_counter_ns() - t0)
            cell.current = prev
            fiber._finish(None, e)
            return
        self.busy_ns.add(time.perf_counter_ns() - t0)
        cell.current = prev
        fiber.state = FIBER_STATE_SUSPENDED
        fiber._resume_value = None
        if token is None:
            # bare `yield` inside legacy generators: treat as yield_now
            self.schedule(fiber, None, to_tail=True)
        else:
            token._register(fiber)

    # -------------------------------------------------------------- misc
    def on_fiber_error(self, fiber: Fiber, exc: BaseException) -> None:
        for h in self._error_handlers:
            try:
                h(fiber, exc)
            except Exception:
                pass
        if not self._error_handlers:
            import logging
            logging.getLogger("brpc_tpu.fiber").exception(
                "fiber %r crashed", fiber.name, exc_info=exc)

    def add_error_handler(self, h: Callable) -> None:
        self._error_handlers.append(h)

    def runqueue_depth(self) -> int:
        """Instantaneous ready-but-not-running fiber count across all
        groups — nonzero under load means requests are waiting for a
        worker (the scheduler-side cause of span queue_us)."""
        return sum(len(g.rq) + len(g.remote_rq) + len(g.bound_rq)
                   for g in self.groups)

    def _saturation_windows(self):
        """Windowed views over busy_ns / runq_peak, created on first
        use (a Window registers with the background sampler — don't
        start that thread for TaskControls nobody inspects)."""
        if self._busy_window is None:
            from brpc_tpu.bvar.window import PerSecond, Window
            self._busy_window = PerSecond(self.busy_ns, 10)
            self._runq_peak_window = Window(self.runq_peak, 10)
        return self._busy_window, self._runq_peak_window

    def worker_busy_fraction(self) -> float:
        """Fraction of worker capacity spent stepping fibers over the
        sampler window: ~1.0 means every worker is saturated and new
        work queues (span queue_us inflates); ~0 means latency lives
        elsewhere (network, handler awaits)."""
        busy, _ = self._saturation_windows()
        per_s = busy.get_value() or 0.0
        if self.concurrency <= 0:
            return 0.0
        return min(1.0, per_s / 1e9 / self.concurrency)

    def saturation_snapshot(self) -> dict:
        """The /status saturation pane's scheduler half."""
        _, peak = self._saturation_windows()
        return {
            "workers": self.concurrency,
            "runqueue_depth": self.runqueue_depth(),
            "runqueue_peak_10s": peak.get_value() or 0,
            "worker_busy_fraction": round(self.worker_busy_fraction(), 4),
        }

    def expose_vars(self, prefix: str = "fiber") -> None:
        self.nfibers.expose(f"{prefix}_count")
        self.nfibers_created.expose(f"{prefix}_created")
        PassiveStatus(lambda: self.concurrency).expose(f"{prefix}_worker_count")
        PassiveStatus(lambda: sum(g.nswitches for g in self.groups)).expose(
            f"{prefix}_switch_count")
        PassiveStatus(lambda: sum(g.nsteals for g in self.groups)).expose(
            f"{prefix}_steal_count")
        # saturation trio (windowed where a point sample would alias):
        # depth is a live gauge; the peak and busy fraction read the
        # sampler's last-10s window (zero-defaulted: an empty window
        # must still render on /vars and the prometheus dump)
        PassiveStatus(self.runqueue_depth).expose(
            f"{prefix}_runqueue_depth")
        _, peak = self._saturation_windows()
        PassiveStatus(lambda: peak.get_value() or 0).expose(
            f"{prefix}_runqueue_peak_10s")
        PassiveStatus(self.worker_busy_fraction).expose(
            f"{prefix}_worker_busy_fraction")


# ----------------------------------------------------------------- globals
_global_control: Optional[TaskControl] = None
_global_lock = threading.Lock()


def global_control() -> TaskControl:
    global _global_control
    if _global_control is None:
        with _global_lock:
            if _global_control is None:
                _global_control = TaskControl()
    return _global_control


def set_concurrency(n: int) -> None:
    """bthread_setconcurrency: must run before the first spawn."""
    global _global_control
    with _global_lock:
        if _global_control is not None and _global_control._started:
            raise RuntimeError("fiber workers already started")
        _global_control = TaskControl(concurrency=n)


def spawn(fn, *args, **kwargs) -> Fiber:
    return global_control().spawn(fn, *args, **kwargs)


def spawn_urgent(fn, *args, **kwargs) -> Fiber:
    return global_control().spawn(fn, *args, urgent=True, **kwargs)


def _postfork_reset() -> None:
    """Fork hygiene: worker pthreads exist only in the parent; the
    inherited TaskControl believes it is _started but owns no threads,
    so every post-fork spawn would queue forever. Drop it (and the
    wake recorder, whose Window rides the parent's sampler) so the
    first post-fork spawn builds a fresh control with live workers."""
    global _global_control, _global_lock, _wake_rec, _wake_rec_lock
    _global_control = None
    _global_lock = threading.Lock()
    _wake_rec = None
    _wake_rec_lock = threading.Lock()
    # the cell registry names PARENT threads; only the forking thread
    # survives — re-register its own cell (its thread-local state
    # itself survives the fork)
    _cell_by_thread.clear()
    _cell_by_thread[threading.get_ident()] = _tls.cell


from brpc_tpu.butil import postfork  # noqa: E402  (registration ships
#                                      with the singleton it resets)

postfork.register("fiber.scheduler", _postfork_reset)


def _fiber_census() -> dict:
    """Resource census: live fiber count off the cheap Adder (the
    gc-walk in fiber.stacks is for on-demand stack dumps only). Peeks —
    a census scrape must not build a TaskControl."""
    c = _global_control
    if c is None:
        return {"count": 0, "workers": 0}
    return {"count": max(0, int(c.nfibers.get_value() or 0)),
            "workers": c.concurrency,
            "runqueue_depth": c.runqueue_depth()}


from brpc_tpu.butil import resource_census as _census  # noqa: E402
#   (census registration ships with the singleton it measures)

_census.register("fibers", _fiber_census)
