"""M:N fiber runtime (bthread equivalent, SURVEY.md §2.2)."""

from brpc_tpu.fiber.scheduler import (
    Fiber, TaskControl, TaskGroup, SchedAwaitable, current_fiber,
    current_group, global_control, set_concurrency, spawn, spawn_urgent,
    yield_now,
)
from brpc_tpu.fiber.butex import Butex, WAIT_OK, WAIT_TIMEOUT, WAIT_VALUE_CHANGED
from brpc_tpu.fiber.sync import (
    CountdownEvent, FiberCondition, FiberEvent, FiberMutex,
)
from brpc_tpu.fiber.timer import (
    PeriodicTask, TimerThread, global_timer, sleep, sleep_us,
)
from brpc_tpu.fiber.execution_queue import ExecutionQueue
from brpc_tpu.fiber.device_poller import DeviceEventPoller, device_ready, global_poller
from brpc_tpu.fiber.keys import FiberLocal

__all__ = [
    "Fiber", "TaskControl", "TaskGroup", "SchedAwaitable", "current_fiber",
    "current_group", "global_control", "set_concurrency", "spawn",
    "spawn_urgent", "yield_now",
    "Butex", "WAIT_OK", "WAIT_TIMEOUT", "WAIT_VALUE_CHANGED",
    "CountdownEvent", "FiberCondition", "FiberEvent", "FiberMutex",
    "PeriodicTask", "TimerThread", "global_timer", "sleep", "sleep_us",
    "ExecutionQueue", "DeviceEventPoller", "device_ready", "global_poller",
    "FiberLocal",
]
