"""TimerThread: one dedicated thread, nearest-deadline sleep
(bthread/timer_thread.h:53). Backs fiber sleeps, RPC timeouts, butex wait
timeouts, and periodic tasks."""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from typing import Callable, Dict, Optional

from brpc_tpu.fiber.scheduler import Fiber, SchedAwaitable


class TimerThread:
    def __init__(self, name: str = "fiber_timer"):
        self._cond = threading.Condition()
        self._heap: list = []          # (deadline, tid, [fn]) — fn boxed so
        #                                unschedule can drop it eagerly
        self._boxes: Dict[int, list] = {}
        self._ndead = 0                # cancelled entries still heaped
        self._seq = itertools.count()
        self._thread: Optional[threading.Thread] = None
        self._stop = False
        self._name = name

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._stop = False
            self._thread = threading.Thread(target=self._run, name=self._name,
                                            daemon=True)
            self._thread.start()

    def schedule_at(self, deadline: float, fn: Callable[[], None]) -> int:
        """deadline is time.monotonic() seconds; returns a timer id."""
        with self._cond:
            tid = next(self._seq)
            box = [fn]
            self._boxes[tid] = box
            # wake the timer thread only when this deadline BEATS the
            # current front: its ongoing sleep already covers any later
            # deadline, and an unconditional notify costs a thread wake
            # per armed RPC deadline (nearest-deadline discipline,
            # timer_thread.cpp)
            wake = not self._heap or deadline < self._heap[0][0]
            heapq.heappush(self._heap, (deadline, tid, box))
            self._ensure_thread()
            if wake:
                self._cond.notify()
        return tid

    def schedule_after(self, delay_s: float, fn: Callable[[], None]) -> int:
        return self.schedule_at(time.monotonic() + max(0.0, delay_s), fn)

    def unschedule(self, tid: int) -> None:
        """Cancel a timer and drop its callback NOW: an RPC deadline
        closure captures the Controller (and any device arrays it holds),
        so retaining it in the heap until the deadline would pin megabytes
        per completed call for the full timeout (seen as recv-pool
        exhaustion under pipelined load)."""
        with self._cond:
            box = self._boxes.pop(tid, None)
            if box is not None:
                box[0] = None
                self._ndead += 1
                # compact when dead entries dominate: without this, a
                # sync RPC stream arming+cancelling a 5s deadline per
                # call leaves thousands of dead fronts that expire
                # together later, and the timer thread's pop-storm
                # preempts the serving path it was protecting (measured
                # as p50 degrading run-over-run on one core)
                if self._ndead > 64 and self._ndead * 2 > len(self._heap):
                    self._heap = [e for e in self._heap
                                  if e[2][0] is not None]
                    heapq.heapify(self._heap)
                    self._ndead = 0

    def _run(self) -> None:
        while not self._stop:
            with self._cond:
                now = time.monotonic()
                while self._heap and self._heap[0][0] <= now:
                    deadline, tid, box = heapq.heappop(self._heap)
                    self._boxes.pop(tid, None)
                    fn = box[0]
                    if fn is None:
                        if self._ndead > 0:
                            self._ndead -= 1
                    else:
                        self._cond.release()
                        try:
                            fn()
                        except Exception:
                            import logging
                            logging.getLogger("brpc_tpu.fiber").exception(
                                "timer callback failed")
                        finally:
                            self._cond.acquire()
                        now = time.monotonic()
                wait = (self._heap[0][0] - now) if self._heap else 1.0
                self._cond.wait(min(max(wait, 0.0), 1.0))

    def pending(self) -> int:
        """Live (non-cancelled) timers in the heap — a per-connection
        timer leak is visible here long before the heap hurts."""
        with self._cond:
            return len(self._boxes)

    def stop(self) -> None:
        self._stop = True
        with self._cond:
            self._cond.notify()


_global_timer: Optional[TimerThread] = None
_lock = threading.Lock()


def global_timer() -> TimerThread:
    global _global_timer
    if _global_timer is None:
        with _lock:
            if _global_timer is None:
                _global_timer = TimerThread()
    return _global_timer


def _postfork_reset() -> None:
    """Fork hygiene: the timer thread died with the parent, and every
    heaped callback closes over parent-side state (RPC deadlines for
    calls the child never issued). Start from an empty heap."""
    global _global_timer, _lock
    _global_timer = None
    _lock = threading.Lock()


from brpc_tpu.butil import postfork  # noqa: E402  (registration ships
#                                      with the singleton it resets)

postfork.register("fiber.timer", _postfork_reset)

from brpc_tpu.butil import resource_census as _census  # noqa: E402
#   (census registration ships with the singleton it measures)

#   peek, never instantiate: a census scrape must not start the thread
_census.register("timers", lambda: {
    "count": _global_timer.pending() if _global_timer is not None else 0})


def sleep(seconds: float) -> SchedAwaitable:
    """Awaitable fiber sleep (bthread_usleep)."""

    class _Sleep(SchedAwaitable):
        def _register(self, fiber: Fiber):
            global_timer().schedule_after(
                seconds, lambda: fiber.control.schedule(fiber, None))
    return _Sleep()


def sleep_us(us: float) -> SchedAwaitable:
    return sleep(us / 1e6)


class PeriodicTask:
    """Re-arms itself after each run (brpc/periodic_task.*)."""

    def __init__(self, interval_s: float, fn: Callable[[], bool | None],
                 timer: Optional[TimerThread] = None):
        self._interval = interval_s
        self._fn = fn
        self._timer = timer or global_timer()
        self._stopped = False
        self._arm()

    def _arm(self):
        self._tid = self._timer.schedule_after(self._interval, self._tick)

    def _tick(self):
        if self._stopped:
            return
        keep = self._fn()
        if keep is not False and not self._stopped:
            self._arm()

    def stop(self):
        self._stopped = True
        self._timer.unschedule(self._tid)
