"""Live fiber stack inspection — the tools/gdb_bthread_stack.py analog.

The reference ships a gdb script that walks TaskMeta contexts of a
running process and prints each bthread's stack. Our fibers are
coroutines: a suspended fiber's whole continuation hangs off
``coro.cr_frame`` / ``cr_await``, so the stacks are recoverable from
Python itself — no debugger required. Discovery goes through the GC
(every live Fiber object) so the spawn hot path pays nothing for this
debug feature.

Surfaces:
  * ``dump_fiber_stacks()``        — text report, importable anywhere
  * ``/fibers?stacks=1``           — same report from the builtin server
  * ``enable_stack_dump_signal()`` — SIGUSR2 prints the report to
    stderr (installed by Server.start when possible), so
    ``tools/fiber_stacks.py <pid>`` works on any serving process the
    way ``gdb -p`` does for the reference
"""

from __future__ import annotations

import gc
import signal
import sys
import traceback
from typing import List, Optional

from brpc_tpu.fiber.scheduler import (FIBER_STATE_DONE, FIBER_STATE_READY,
                                      FIBER_STATE_RUNNING,
                                      FIBER_STATE_SUSPENDED, Fiber)

_STATE_NAMES = {
    FIBER_STATE_READY: "READY",
    FIBER_STATE_RUNNING: "RUNNING",
    FIBER_STATE_SUSPENDED: "SUSPENDED",
    FIBER_STATE_DONE: "DONE",
}


def _coro_frames(coro) -> List:
    """Walk a suspended coroutine's await chain innermost-last."""
    frames = []
    seen = set()
    while coro is not None and id(coro) not in seen:
        seen.add(id(coro))
        frame = getattr(coro, "cr_frame", None) or \
            getattr(coro, "gi_frame", None)
        if frame is not None:
            frames.append(frame)
        coro = getattr(coro, "cr_await", None) or \
            getattr(coro, "gi_yieldfrom", None)
    return frames


def live_fibers() -> List[Fiber]:
    return [o for o in gc.get_objects()
            if type(o) is Fiber and o.state != FIBER_STATE_DONE]


def dump_fiber_stacks(include_ready: bool = True) -> str:
    """One block per live fiber: name, state, and the Python stack its
    continuation is parked on (RUNNING fibers show no stack here —
    they're on some thread's C stack; see /threads for those)."""
    out = []
    fibers = live_fibers()
    if not include_ready:
        fibers = [f for f in fibers if f.state != FIBER_STATE_READY]
    out.append(f"{len(fibers)} live fibers\n")
    for f in fibers:
        state = _STATE_NAMES.get(f.state, str(f.state))
        out.append(f"\n--- fiber {f.name or '<unnamed>'} [{state}]\n")
        if f.state == FIBER_STATE_RUNNING:
            out.append("  (executing on a worker thread — /threads "
                       "shows thread stacks)\n")
            continue
        frames = _coro_frames(f.coro)
        if not frames:
            out.append("  (not started)\n")
            continue
        for frame in frames:
            out.extend("  " + ln for ln in
                       traceback.format_stack(frame, limit=1))
    return "".join(out)


_installed = [False]


def enable_stack_dump_signal(signum: int = signal.SIGUSR2) -> bool:
    """SIGUSR2 -> fiber stack report on stderr. Main-thread only (a
    CPython restriction); returns False when it can't install — callers
    treat this as best-effort (tools/fiber_stacks.py says so too)."""
    if _installed[0]:
        return True

    def _dump(sig, frm):
        try:
            sys.stderr.write(dump_fiber_stacks())
            sys.stderr.flush()
        except Exception:
            pass

    try:
        # never displace an application's own handler — only claim the
        # default disposition (the reference's gdb script needs no
        # in-process hook at all; this one stays polite)
        if signal.getsignal(signum) not in (signal.SIG_DFL, None):
            return False
        signal.signal(signum, _dump)
    except ValueError:      # not the main thread
        return False
    _installed[0] = True
    return True
