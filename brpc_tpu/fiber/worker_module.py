"""Worker modules: co-schedule an external engine with the fiber workers
(the fork's EloqModule hook, eloq_module.h:60-64 + TaskGroup::
NotifyRegisteredModules — modules register process/has_task callbacks
that every worker's main loop polls, so a database/compute engine shares
the worker threads instead of fighting them).

    class MyEngine(WorkerModule):
        def has_task(self): ...
        def process(self, group_index): ...   # run a slice of work
    register_module(MyEngine())

``on_worker_start/on_worker_stop`` mirror ExtThdStart/ExtThdEnd."""

from __future__ import annotations

import threading
from typing import List


class WorkerModule:
    def has_task(self) -> bool:
        """Cheap check: is there engine work pending?"""
        return False

    def process(self, group_index: int) -> None:
        """Run a bounded slice of engine work on this worker."""

    def on_worker_start(self, group_index: int) -> None:
        """Called once per worker thread before its loop."""

    def on_worker_stop(self, group_index: int) -> None:
        """Called once per worker thread after its loop."""


_modules: List[WorkerModule] = []
_lock = threading.Lock()


def register_module(module: WorkerModule) -> None:
    with _lock:
        _modules.append(module)


def unregister_module(module: WorkerModule) -> None:
    with _lock:
        try:
            _modules.remove(module)
        except ValueError:
            pass


def registered_modules() -> List[WorkerModule]:
    return list(_modules)


def has_modules() -> bool:
    """Allocation-free emptiness check for the worker hot loop."""
    return bool(_modules)


def process_modules(group_index: int) -> bool:
    """One pass over registered modules from a worker loop; True if any
    ran work (the worker then skips parking this round)."""
    ran = False
    for m in _modules:
        try:
            if m.has_task():
                m.process(group_index)
                ran = True
        except Exception:
            import logging
            logging.getLogger("brpc_tpu.fiber").exception(
                "worker module failed")
    return ran


def notify_start(group_index: int) -> None:
    for m in _modules:
        try:
            m.on_worker_start(group_index)
        except Exception:
            pass


def notify_stop(group_index: int) -> None:
    for m in _modules:
        try:
            m.on_worker_stop(group_index)
        except Exception:
            pass
