"""Worker modules: co-schedule an external engine with the fiber workers
(the fork's EloqModule hook, eloq_module.h:60-64 + TaskGroup::
NotifyRegisteredModules — modules register process/has_task callbacks
that every worker's main loop polls, so a database/compute engine shares
the worker threads instead of fighting them).

    class MyEngine(WorkerModule):
        def has_task(self): ...
        def process(self, group_index): ...   # run a slice of work
    register_module(MyEngine())

``on_worker_start/on_worker_stop`` mirror ExtThdStart/ExtThdEnd."""

from __future__ import annotations

import threading
from typing import Dict, List, Optional


class WorkerModule:
    def has_task(self) -> bool:
        """Cheap check: is there engine work pending?"""
        return False

    def process(self, group_index: int):
        """Run a bounded slice of engine work on this worker.

        Return ``False`` to report that NO progress was made (e.g. a
        sibling worker already holds the engine's slice lock): the
        worker loop then treats this round as idle and may park instead
        of hot-spinning on work it cannot touch. Any other return
        (including the default None) counts as progress."""

    def on_worker_start(self, group_index: int) -> None:
        """Called once per worker thread before its loop."""

    def on_worker_stop(self, group_index: int) -> None:
        """Called once per worker thread after its loop."""


_modules: List[WorkerModule] = []
_lock = threading.Lock()
# thread-id -> attribution label while that thread is inside a module's
# process() slice: the flight recorder's sampler attributes busy samples
# landing in engine work to the module's declared label (engine slices
# run OUTSIDE any fiber, so the fiber-local attribution hooks miss them)
_active: Dict[int, str] = {}


def register_module(module: WorkerModule) -> None:
    with _lock:
        _modules.append(module)


def unregister_module(module: WorkerModule) -> None:
    with _lock:
        try:
            _modules.remove(module)
        except ValueError:
            pass


def registered_modules() -> List[WorkerModule]:
    return list(_modules)


def has_modules() -> bool:
    """Allocation-free emptiness check for the worker hot loop."""
    return bool(_modules)


def process_modules(group_index: int) -> bool:
    """One pass over registered modules from a worker loop; True if any
    ran work (the worker then skips parking this round). A module whose
    ``process`` returns False reported a no-progress slice and does NOT
    keep the worker awake."""
    ran = False
    tid = threading.get_ident()
    for m in _modules:
        try:
            if m.has_task():
                label = getattr(m, "attribution_label", None)
                if label is not None:
                    _active[tid] = label
                try:
                    r = m.process(group_index)
                finally:
                    if label is not None:
                        _active.pop(tid, None)
                if r is not False:
                    ran = True
        except Exception:
            import logging
            logging.getLogger("brpc_tpu.fiber").exception(
                "worker module failed")
    return ran


def active_label(tid: int) -> Optional[str]:
    """The attribution label of the module slice thread ``tid`` is
    currently inside, if any (read by the flight-recorder sampler)."""
    return _active.get(tid)


def notify_start(group_index: int) -> None:
    for m in _modules:
        try:
            m.on_worker_start(group_index)
        except Exception:
            pass


def notify_stop(group_index: int) -> None:
    for m in _modules:
        try:
            m.on_worker_stop(group_index)
        except Exception:
            pass


def _postfork_reset() -> None:
    """A forked shard must NOT inherit the parent's registered engines:
    the parent's modules hold state (locks, batch arrays, controllers)
    owned by threads that no longer exist, and the child's fresh worker
    loops would double-run them against the parent's requests. Each
    shard re-registers its own engine when its server starts."""
    global _modules, _lock, _active
    _modules = []
    _lock = threading.Lock()
    _active = {}


from brpc_tpu.butil import postfork  # noqa: E402  (registration ships
#                                      with the registry it resets)

postfork.register("fiber.worker_module", _postfork_reset)
