"""Butex: the futex of the fiber runtime (bthread/butex.h:36-71).

A 32-bit-word-with-wait-queue that both fibers AND plain threads can block
on — the foundation of every blocking primitive (mutex, cond, countdown,
join, correlation ids), exactly as in the reference.

Fiber waiters:  ``await butex.wait(expected)`` — suspends the fiber unless
                the value already differs; wake pushes it back to a run
                queue (the value re-check happens under the butex lock at
                registration, closing the check-then-sleep race the same
                way butex_wait's value test does).
Thread waiters: ``butex.wait_pthread(expected, timeout)`` parks the OS
                thread on an Event (the reference's pthread waiter path).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Deque, Optional

from brpc_tpu.fiber.scheduler import Fiber, SchedAwaitable

WAIT_OK = "ok"
WAIT_VALUE_CHANGED = "value_changed"
WAIT_TIMEOUT = "timeout"


class _FiberWaiter:
    __slots__ = ("fiber", "timer_id", "active")

    def __init__(self, fiber: Fiber):
        self.fiber = fiber
        self.timer_id = None
        self.active = True


class Butex:
    def __init__(self, value: int = 0):
        self._value = value
        self._lock = threading.Lock()
        self._fiber_waiters: Deque[_FiberWaiter] = deque()
        self._thread_waiters: Deque[threading.Event] = deque()

    # -------------------------------------------------------------- value
    @property
    def value(self) -> int:
        return self._value

    def set_value(self, v: int) -> None:
        with self._lock:
            self._value = v

    def fetch_add(self, delta: int) -> int:
        with self._lock:
            old = self._value
            self._value = old + delta
            return old

    def compare_exchange(self, expected: int, new: int) -> bool:
        with self._lock:
            if self._value != expected:
                return False
            self._value = new
            return True

    # --------------------------------------------------------------- wait
    def wait(self, expected: int, timeout_s: Optional[float] = None) -> SchedAwaitable:
        """Awaitable: park current fiber while value == expected.
        Resumes with WAIT_OK / WAIT_VALUE_CHANGED / WAIT_TIMEOUT."""
        butex = self

        class _Wait(SchedAwaitable):
            def _register(self, fiber: Fiber):
                butex.add_waiter(fiber, expected, timeout_s)
        return _Wait()

    def add_waiter(self, fiber: Fiber, expected: int,
                   timeout_s: Optional[float] = None) -> None:
        """Register a suspended fiber; wakes it immediately if the value
        already changed (the butex_wait value test)."""
        with self._lock:
            if self._value != expected:
                fiber.control.schedule(fiber, WAIT_VALUE_CHANGED)
                return
            w = _FiberWaiter(fiber)
            self._fiber_waiters.append(w)
        if timeout_s is not None:
            from brpc_tpu.fiber.timer import global_timer
            w.timer_id = global_timer().schedule_after(
                timeout_s, lambda: self._on_timeout(w))

    def _on_timeout(self, w: _FiberWaiter) -> None:
        with self._lock:
            if not w.active:
                return
            w.active = False
            try:
                self._fiber_waiters.remove(w)
            except ValueError:
                return
        w.fiber.control.schedule(w.fiber, WAIT_TIMEOUT)

    def wait_pthread(self, expected: int, timeout_s: Optional[float] = None) -> str:
        """Blocking wait for plain threads."""
        with self._lock:
            if self._value != expected:
                return WAIT_VALUE_CHANGED
            ev = threading.Event()
            self._thread_waiters.append(ev)
        if ev.wait(timeout_s):
            return WAIT_OK
        with self._lock:
            try:
                self._thread_waiters.remove(ev)
            except ValueError:
                return WAIT_OK  # woken concurrently with the timeout
        return WAIT_TIMEOUT

    # --------------------------------------------------------------- wake
    def wake(self, n: int = 1) -> int:
        """Wake up to n waiters (fibers first); returns number woken."""
        fibers = []
        events = []
        with self._lock:
            while n > 0 and self._fiber_waiters:
                w = self._fiber_waiters.popleft()
                w.active = False
                fibers.append(w)
                n -= 1
            while n > 0 and self._thread_waiters:
                events.append(self._thread_waiters.popleft())
                n -= 1
        for w in fibers:
            if w.timer_id is not None:
                from brpc_tpu.fiber.timer import global_timer
                global_timer().unschedule(w.timer_id)
            w.fiber.control.schedule(w.fiber, WAIT_OK)
        for ev in events:
            ev.set()
        return len(fibers) + len(events)

    def wake_all(self) -> int:
        return self.wake(1 << 30)

    def set_and_wake_all(self, value: int) -> int:
        with self._lock:
            self._value = value
        return self.wake_all()
