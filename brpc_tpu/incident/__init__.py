"""Incident time machine: capture-on-anomaly, bounded artifacts, and
deterministic local reproduction.

The watchdog (bvar/anomaly.py) detects the break; the traffic recorder
(traffic/capture.py) knows how to record and warp-replay a corpus; this
package connects them. When an incident opens, the manager flips the
recorder into corpus-recording mode for a bounded tick window, then
bundles the in-window corpus plus the observability snapshots that
explain it (/timeline slice for the triggering keys, folded profile,
/status, /device, /backends, the annotated rpcz spans) into one
size-capped ``.brpcinc`` artifact under a disk budget. The other half
(incident/replay.py, tools/incident_replay.py) turns an artifact back
into a failing local run: derive a seeded chaos FaultPlan from the
incident's error classes, replay the corpus against a fresh server
under that plan, and assert the watchdog re-fires on the same key.
"""

from brpc_tpu.incident.artifact import (ArtifactWriter, SUFFIX,
                                        artifact_files, artifact_summary,
                                        read_artifact)
from brpc_tpu.incident.manager import (IncidentManager,
                                       attach_incident_server,
                                       bind_incident_imports,
                                       expose_incident_vars,
                                       global_manager,
                                       incident_sample_tick,
                                       incident_status_line,
                                       incidents_snapshot_payload)

__all__ = [
    "ArtifactWriter", "SUFFIX", "artifact_files", "artifact_summary",
    "read_artifact", "IncidentManager", "attach_incident_server",
    "bind_incident_imports", "expose_incident_vars", "global_manager",
    "incident_sample_tick", "incident_status_line",
    "incidents_snapshot_payload",
]
