"""Deterministic local reproduction of a frozen incident.

The replay half of the incident time machine: take a ``.brpcinc``
artifact, derive the *pressure* that plausibly caused it from the
incident's error classes and trigger keys, re-apply that pressure to a
fresh loopback server replaying the captured corpus, and assert the
anomaly watchdog re-fires on the same key. The fix-forward run — the
same replay WITHOUT the derived pressure — must stay green; together
the pair is a regression test distilled from production evidence.

Derivation map (ISSUE 17):

  ERPCTIMEDOUT / *deadline_shed / *queue_delay keys
      → chaos ``delay``/``partial_stall`` byte faults on the request
        path (seeded FaultPlan)
  EFAILEDSOCKET / ECLOSE (connect errors)
      → chaos ``refuse``/``flap`` connection faults
  EOVERCROWDED / ELIMIT / EPRIORITYSHED / *limit_shed /
  *overcrowded keys
      → PRESS overload: open-loop pacing at a multiple of the
        server's estimated capacity (no byte fault can make a server
        shed; offered load does)

The press/calm pacing derives from ONE estimate — the corpus's median
recorded service latency — so the faulted run offers
``press_factor``× the server's capacity and the fix-forward run
offers ``calm_factor``× (deterministically under it). The fresh
server replicates the incident server's shape from the artifact's
/status snapshot (concurrency limit), so "re-fires on the same key"
is a statement about the same overload organ, not a lucky race.

This is OFFLINE tool code (tools/incident_replay.py, the smoke, the
tier-1 test) — never sampler or dispatch path.
"""

from __future__ import annotations

import json
import time
from typing import Dict, List, Optional

from brpc_tpu.chaos.plan import Fault, FaultPlan
from brpc_tpu.incident.artifact import read_artifact
from brpc_tpu.rpc import errno_codes as berr
from brpc_tpu.traffic.corpus import CapturedRequest
from brpc_tpu.traffic.replay import PaceSpec, run_open_loop

_TIMEOUT_CLASSES = {berr.ERPCTIMEDOUT}
_CONNECT_CLASSES = {berr.EFAILEDSOCKET, berr.ECLOSE}
_PRESS_CLASSES = {berr.EOVERCROWDED, berr.ELIMIT, berr.EPRIORITYSHED}
_MIN_PRESS_RECORDS = 64


def _class_codes(meta: dict) -> set:
    """The incident document's error classes as integer codes (the
    document stores errno NAMES — human-readable in the artifact)."""
    out = set()
    for name in (meta.get("error_classes") or {}):
        code = getattr(berr, name, None)
        if isinstance(code, int):
            out.add(code)
        elif name.startswith("E") and name[1:].isdigit():
            out.add(int(name[1:]))
    return out


def derive_repro(meta: dict, seed: int = 0) -> dict:
    """Classify the pressure an incident implies. Pure function of the
    incident document (error classes + trigger keys) — the endpoint
    addressing happens later, when the fresh server exists."""
    codes = _class_codes(meta)
    keys = [str(k) for k in (meta.get("keys") or ())]
    if meta.get("peak_key"):
        keys.append(str(meta["peak_key"]))
    press = bool(codes & _PRESS_CLASSES) or any(
        "limit_shed" in k or "overcrowded" in k or "priority_shed" in k
        for k in keys)
    timeouts = bool(codes & _TIMEOUT_CLASSES) or any(
        "deadline_shed" in k or "queue_delay" in k for k in keys)
    connect = bool(codes & _CONNECT_CLASSES)
    return {"seed": seed, "press": press, "timeouts": timeouts,
            "connect": connect,
            "classes": sorted(berr.errno_name(c) for c in codes)}


def build_fault_plan(shape: dict, endpoint: str,
                     conns: int = 4) -> Optional[FaultPlan]:
    """The seeded chaos FaultPlan for the byte/connection half of the
    derivation, addressed at the fresh server's endpoint. None when
    the shape needs no transport faults (pure press overload)."""
    plan = FaultPlan(seed=int(shape.get("seed", 0)))
    used = False
    if shape.get("timeouts"):
        # hold every connection's first request bytes long enough to
        # blow a recorded deadline; one connection gets the
        # half-written-frame stall (the worst flavor)
        for idx in range(conns):
            plan.at(endpoint, idx,
                    Fault("delay", at_byte=1, delay_ms=150.0))
        plan.at(endpoint, conns, Fault("partial_stall", at_byte=16))
        used = True
    if shape.get("connect"):
        plan.refuse(endpoint, 0)
        plan.flap(endpoint, at_conn=2, refuse_next=2)
        used = True
    return plan if used else None


def _estimate_work_ms(records: List[CapturedRequest]) -> float:
    """Median recorded service latency of the corpus's OK requests —
    the one number press/calm pacing scales from."""
    lats = sorted(r.latency_us for r in records
                  if not r.status and r.latency_us > 0)
    if not lats:
        return 5.0
    med = lats[len(lats) // 2] / 1000.0
    return max(2.0, min(50.0, med))


def _replayable(records: List[CapturedRequest]) -> List[CapturedRequest]:
    return [r for r in records
            if r.service and r.service != "builtin"
            and not r.service.startswith("__")]


def _tile(records: List[CapturedRequest],
          n: int) -> List[CapturedRequest]:
    """Press mode multiplies a short window corpus up to ``n`` issues:
    overload is a statement about offered RATE, and a dozen records
    cannot offer a rate for long enough to spike a whole tick
    bucket."""
    out = list(records)
    while len(out) < n:
        out.extend(records)
    return out[:max(n, len(records))]


def replay_incident(artifact_path: str, use_plan: bool = True,
                    seed: int = 7, warmup_ticks: int = 3,
                    press_factor: float = 4.0,
                    calm_factor: float = 0.5,
                    conns: int = 4,
                    server_factory=None) -> dict:
    """One-command reproduction: fresh loopback server shaped from the
    artifact's /status snapshot, corpus replayed under the derived
    pressure (``use_plan=True``) or without it (the fix-forward run),
    watchdog pinned to the incident's trigger keys. Returns a report;
    ``report["refired"]`` is the verdict."""
    from brpc_tpu.butil.flags import flag, set_flag
    from brpc_tpu.bvar.anomaly import global_watchdog
    from brpc_tpu.bvar.series import series_sample_tick
    from brpc_tpu.chaos import inject as chaos_inject
    from brpc_tpu.fiber.timer import sleep as fiber_sleep
    from brpc_tpu.rpc import Server, ServerOptions, Service

    art = read_artifact(artifact_path)
    meta = art["meta"]
    records = _replayable(art["corpus"])
    trigger_keys = [str(k) for k in (meta.get("keys") or ())]
    peak_key = str(meta.get("peak_key") or
                   (trigger_keys[0] if trigger_keys else ""))
    if peak_key and peak_key not in trigger_keys:
        trigger_keys.append(peak_key)
    report: dict = {
        "artifact": artifact_path,
        "incident_id": meta.get("id"),
        "trigger_keys": trigger_keys, "peak_key": peak_key,
        "corpus_records": len(records), "use_plan": use_plan,
        "seed": seed,
    }
    if not records or not trigger_keys:
        report["ok"] = False
        report["error"] = ("artifact has no replayable corpus"
                           if not records
                           else "artifact names no trigger keys")
        report["refired"] = False
        return report

    shape = derive_repro(meta, seed=seed)
    report["derived"] = shape
    work_ms = _estimate_work_ms(records)
    report["work_ms"] = round(work_ms, 2)
    capacity_qps = 1000.0 / work_ms
    status_snap = (art["snapshots"].get("status") or {}) \
        if isinstance(art.get("snapshots"), dict) else {}
    sat = status_snap.get("saturation") or {}

    # ---- watchdog: pinned filter, fresh baselines, no re-arming
    saved = {f: flag(f) for f in (
        "anomaly_watch_filter", "anomaly_warmup_ticks",
        "anomaly_close_ticks", "anomaly_watchdog_enabled",
        "incident_capture_enabled")}
    set_flag("anomaly_watch_filter", ",".join(sorted(set(trigger_keys))))
    set_flag("anomaly_warmup_ticks", str(warmup_ticks))
    set_flag("anomaly_close_ticks", "3")
    set_flag("anomaly_watchdog_enabled", "true")
    set_flag("incident_capture_enabled", "false")
    wd = global_watchdog()
    wd.reset()

    server = None
    plan = None
    installed = False
    try:
        if server_factory is not None:
            server, address = server_factory()
        else:
            opts = ServerOptions(enable_builtin_services=False)
            limit = sat.get("concurrency_limit")
            if shape["press"]:
                # replicate the incident server's overload organ: its
                # concurrency limit, floored at 1 (a press repro
                # against an unlimited server sheds nothing)
                opts.max_concurrency = int(limit) if limit else 1
            server = Server(opts)
            svc_by_name: Dict[str, Service] = {}
            work_s = work_ms / 1000.0

            def _mk_handler(delay_s: float):
                async def replay_echo_handler(cntl, request):
                    await fiber_sleep(delay_s)
                    return bytes(request)
                return replay_echo_handler

            for rec in records:
                svc = svc_by_name.get(rec.service)
                if svc is None:
                    svc = svc_by_name[rec.service] = Service(rec.service)
                    server.add_service(svc)
                if rec.method not in svc.methods:
                    svc.register_method(rec.method, _mk_handler(work_s))
            ep = server.start("tcp://127.0.0.1:0")
            address = f"tcp://127.0.0.1:{ep.port}"
        report["address"] = address

        if use_plan:
            plan = build_fault_plan(shape, address, conns=conns)
            if plan is not None:
                chaos_inject.install(plan)
                installed = True
                report["plan"] = json.loads(plan.to_json())

        # warmup: zero-traffic baselines for the pinned keys
        t0 = int(time.time())
        for i in range(warmup_ticks + 1):
            series_sample_tick(wall_t=t0 + i)
        before = len(wd.incident_snapshot())

        if shape["press"] and use_plan:
            replay_records = _tile(records, _MIN_PRESS_RECORDS)
            pace = PaceSpec("qps", qps=press_factor * capacity_qps,
                            seed=seed)
        elif shape["press"]:
            # fix-forward: same corpus, offered rate deterministically
            # UNDER capacity (evenly spaced issues at calm_factor of
            # the service rate never overlap on a drained server)
            replay_records = records
            pace = PaceSpec("qps", qps=calm_factor * capacity_qps,
                            seed=seed)
        else:
            replay_records = records
            pace = PaceSpec("recorded", warp=1.0, seed=seed)
        rep = run_open_loop(
            replay_records, address, pace, conns=conns,
            default_timeout_ms=max(500.0, 20 * work_ms),
            drain_s=5.0)
        report["replay"] = {
            "records": rep.get("records"), "issued": rep.get("issued"),
            "ok": rep.get("ok"), "fail": rep.get("fail"),
            "elapsed_s": rep.get("elapsed_s"),
            "per_method": rep.get("per_method"),
            "pace": rep.get("pace"),
        }
        if plan is not None:
            report["plan_fired"] = len(plan.fired())

        # the spike's bucket, plus one settling tick
        for i in range(2):
            series_sample_tick(wall_t=t0 + warmup_ticks + 1 + i)
        incidents = wd.incident_snapshot()[before:]
        matched = [inc for inc in incidents
                   if set(inc.get("keys") or ())
                   & set(trigger_keys)]
        report["incidents_opened"] = len(incidents)
        report["refired"] = bool(matched)
        if matched:
            report["matched_key"] = (
                matched[0].get("peak_key")
                or (matched[0].get("keys") or [""])[0])
        report["ok"] = True
        return report
    finally:
        if installed:
            try:
                chaos_inject.uninstall()
            except Exception:
                pass
        if server is not None and server_factory is None:
            try:
                server.stop()
                server.join(2)
            except Exception:
                pass
        for f, v in saved.items():
            try:
                set_flag(f, str(v))
            except Exception:
                pass
        wd.reset()
