"""The .brpcinc incident artifact: one frozen anomaly, one file.

An artifact is a recordio container (butil/recordio.py — the same
length-prefixed, crc32c-checksummed discipline as the .brpccap corpus)
holding three record species, distinguished by their meta JSON:

    {"inc":"meta","v":1}          data = JSON incident document
                                  (trigger keys, window stamps, error
                                  classes, corpus accounting)
    {"inc":"snap","name":<name>}  data = JSON snapshot (status,
                                  timeline slice, hotspots profile,
                                  device, backends, rpcz spans)
    corpus meta  {k,s,n,...}      data = payload||attachment — the
                                  in-window captured requests, encoded
                                  EXACTLY as traffic/corpus.py records

The corpus species being wire-identical to .brpccap is the point:
``traffic.corpus.CorpusReader`` over a .brpcinc file skips the foreign
meta/snap records (decode_record returns None on unknown meta) and
yields the captured requests — every corpus tool (rpc_view summaries,
replay) works on an incident artifact unchanged.

A sidecar ``<artifact>.idx`` (JSON) gives pages and tools an O(1)
summary; like the corpus index it is advisory — validated against the
file size and rebuilt by scanning when missing or stale.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

from brpc_tpu.butil.recordio import RecordReader, RecordWriter
from brpc_tpu.traffic.corpus import (CapturedRequest, decode_record,
                                     encode_meta)

SUFFIX = ".brpcinc"
INDEX_SUFFIX = ".idx"
_INDEX_VERSION = 1
_ARTIFACT_VERSION = 1


class ArtifactWriter:
    """Append-assemble one incident artifact. Single-owner by protocol
    (the incident bundler thread); tracks bytes written so the bundler
    can stop adding corpus records at the size cap."""

    def __init__(self, path: str):
        self.path = path
        # TRUNCATES: one bundler owns an artifact for its whole life
        self._f = open(path, "wb", buffering=1 << 20)
        self._w = RecordWriter(self._f)
        self.bytes = 0
        self.corpus_records = 0
        self.snapshot_names: List[str] = []
        self._meta_doc: Optional[dict] = None

    def put_incident_meta(self, doc: dict) -> int:
        """The incident document — write it FIRST so a size-capped or
        torn artifact still identifies its incident."""
        self._meta_doc = doc
        meta = json.dumps({"inc": "meta", "v": _ARTIFACT_VERSION},
                          separators=(",", ":")).encode()
        data = json.dumps(doc, sort_keys=True,
                          separators=(",", ":")).encode()
        n = self._w.write_chunks((data,), meta)
        self.bytes += n
        return n

    def put_snapshot(self, name: str, doc) -> int:
        meta = json.dumps({"inc": "snap", "name": name},
                          separators=(",", ":")).encode()
        data = json.dumps(doc, separators=(",", ":"),
                          default=str).encode()
        n = self._w.write_chunks((data,), meta)
        self.bytes += n
        self.snapshot_names.append(name)
        return n

    def put_request(self, rec: CapturedRequest) -> int:
        """One in-window captured request, encoded exactly as a
        .brpccap record (CorpusReader-compatible)."""
        n = self._w.write_chunks((rec.payload, rec.attachment),
                                 encode_meta(rec))
        self.bytes += n
        self.corpus_records += 1
        return n

    def close(self) -> None:
        if self._f.closed:
            return
        self._f.flush()
        size = self._f.tell()
        self._f.close()
        # advisory sidecar: pages/tools summarize without a scan
        md = self._meta_doc or {}
        try:
            tmp = self.path + INDEX_SUFFIX + f".tmp.{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump({
                    "version": _INDEX_VERSION, "file_size": size,
                    "corpus_records": self.corpus_records,
                    "snapshots": list(self.snapshot_names),
                    "incident_id": md.get("id"),
                    "peak_key": md.get("peak_key"),
                    "keys": md.get("keys"),
                    "opened_t": md.get("opened_t"),
                }, f)
            os.replace(tmp, self.path + INDEX_SUFFIX)
        except OSError:
            pass


def read_artifact(path: str) -> dict:
    """Parse a whole artifact: ``{"meta": incident doc, "snapshots":
    {name: doc}, "corpus": [CapturedRequest], "bad_records": n}``.
    Resyncs past corruption (recordio semantics); a torn tail loses at
    most the final record."""
    meta_doc: Optional[dict] = None
    snapshots: Dict[str, object] = {}
    corpus: List[CapturedRequest] = []
    bad = 0
    with open(path, "rb") as f:
        for meta, data in RecordReader(f):
            kind = None
            try:
                m = json.loads(meta)
                kind = m.get("inc") if isinstance(m, dict) else None
            except ValueError:
                m = None
            if kind == "meta":
                try:
                    meta_doc = json.loads(data)
                except ValueError:
                    bad += 1
            elif kind == "snap":
                try:
                    snapshots[m.get("name") or ""] = json.loads(data)
                except ValueError:
                    bad += 1
            else:
                rec = decode_record(meta, data)
                if rec is None:
                    bad += 1
                else:
                    corpus.append(rec)
    return {"meta": meta_doc or {}, "snapshots": snapshots,
            "corpus": corpus, "bad_records": bad}


def artifact_summary(path: str) -> dict:
    """O(1) summary from the sidecar when it matches the artifact's
    byte size; full scan otherwise."""
    try:
        size = os.stat(path).st_size
    except OSError:
        size = -1
    try:
        with open(path + INDEX_SUFFIX, encoding="utf-8") as f:
            idx = json.load(f)
        if idx.get("version") == _INDEX_VERSION \
                and idx.get("file_size") == size:
            idx["source"] = "sidecar"
            return idx
    except (OSError, ValueError):
        pass
    art = read_artifact(path)
    md = art["meta"]
    return {"version": _INDEX_VERSION, "file_size": size,
            "corpus_records": len(art["corpus"]),
            "snapshots": sorted(art["snapshots"]),
            "incident_id": md.get("id"), "peak_key": md.get("peak_key"),
            "keys": md.get("keys"), "opened_t": md.get("opened_t"),
            "source": "scan", "bad_records": art["bad_records"]}


def artifact_files(dirpath: str) -> List[str]:
    """All artifacts under an incident dir, oldest mtime first (the
    disk-budget eviction order)."""
    try:
        names = [n for n in os.listdir(dirpath) if n.endswith(SUFFIX)]
    except OSError:
        return []
    paths = [os.path.join(dirpath, n) for n in names]

    def _stamp(p: str):
        try:
            return (os.stat(p).st_mtime, p)
        except OSError:
            return (0.0, p)

    paths.sort(key=_stamp)
    return paths
