"""The incident manager: the glue between the anomaly watchdog and the
traffic recorder, and the keeper of the bounded incident-artifact
store.

Lifecycle (all decisions ride the sampler tick; all disk work happens
on a dedicated bundler thread):

  1. ``bvar/anomaly.py`` finishes a watchdog pass and hands every
     tick's (opened, closed) incident transitions to
     ``incident_sample_tick``.  Idle cost is ONE attribute check — no
     flag read, no lock (the "arming is one flag check per tick"
     contract).
  2. An OPENING incident arms a bounded capture window: the traffic
     recorder flips into corpus-recording mode (``max_per_second=0``,
     sample rate 1.0) into a per-incident spool dir via
     ``Recorder.begin_incident_capture`` — which saves the operator's
     live capture session for restore, the satellite bugfix.
  3. The window closes when the watchdog closes the incident OR after
     ``incident_window_ticks`` ticks, whichever comes first (bounded
     evidence, not open-ended recording).  Sealing spawns the bundler
     thread — named WITHOUT a sampler marker so graftlint's
     sampler-no-lazy-import walk does not claim it, though it keeps
     the same discipline anyway (module-level imports only).
  4. The bundler restores the recorder, reads the spool, and writes
     one size-capped ``.brpcinc`` artifact: incident document +
     /status //device //backends //timeline-slice /hotspots snapshots
     + the annotated rpcz spans + the in-window corpus.  The spool is
     deleted; the artifact dir is held under
     ``incident_disk_budget_mb`` by evicting oldest artifacts first.

Collaborator modules (builtin.services, flight_recorder, span,
device_stats, backend_stats) are bound on the CALLER thread by
``bind_incident_imports()`` — called from
``anomaly.bind_watchdog_imports`` (Server.start via
series.ensure_series), the PR 13 idiom — never imported at sample
time.

``IncidentManager._lock`` is a LEAF (LOCK_ORDER row:
incident/manager.py): it guards window/artifact bookkeeping only;
recorder control, disk work and snapshot building all happen outside
it.
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import threading
import time
import weakref
from typing import List, Optional

from brpc_tpu.butil import postfork
from brpc_tpu.butil.flags import define_flag, flag
from brpc_tpu.bvar.reducer import Adder, PassiveStatus
from brpc_tpu.incident.artifact import (SUFFIX, ArtifactWriter,
                                        artifact_files, artifact_summary)
from brpc_tpu.rpc.errno_codes import errno_name
from brpc_tpu.traffic import capture as _capture
from brpc_tpu.traffic.corpus import read_corpus

# env-sensitive default: the overhead smoke A/B-toggles arming on
# spawned servers through BRPC_TPU_INCIDENT_ARM without touching flags
define_flag("incident_capture_enabled",
            os.environ.get("BRPC_TPU_INCIDENT_ARM", "1") != "0",
            "arm capture-on-anomaly: an opening watchdog incident "
            "flips the traffic recorder into corpus-recording mode "
            "for a bounded window and bundles an incident artifact")
define_flag("incident_dir", "",
            "directory for incident artifacts and capture spools "
            "(empty = incident capture off even when armed)")
define_flag("incident_window_ticks", 8,
            "sampler ticks an incident capture window stays open when "
            "the incident itself does not close first",
            validator=lambda v: v >= 1)
define_flag("incident_max_artifact_mb", 16,
            "size cap for one incident artifact (corpus records stop "
            "appending at the cap; the incident document and "
            "snapshots always fit first)",
            validator=lambda v: v >= 1)
define_flag("incident_disk_budget_mb", 64,
            "delete oldest incident artifacts past this total",
            validator=lambda v: v >= 1)
define_flag("incident_max_corpus_records", 8192,
            "in-window captured requests bundled into one artifact "
            "(oldest kept — the requests that led INTO the break)",
            validator=lambda v: v >= 1)

# collaborators bound on the caller thread (bind_incident_imports);
# never imported on the sampler tick or the bundler thread
_services_mod = None       # builtin.services (status/timeline builders)
_fr_mod = None             # builtin.flight_recorder
_span_mod = None           # rpc.span
_device_mod = None         # transport.device_stats
_backend_mod = None        # rpc.backend_stats

_SPAN_BUNDLE_MAX = 32


def bind_incident_imports() -> None:
    """One-time import binding for the bundler's snapshot builders;
    runs on the thread that starts the serving stack (Server.start →
    ensure_series → bind_watchdog_imports → here)."""
    global _services_mod, _fr_mod, _span_mod, _device_mod, _backend_mod
    if _services_mod is not None:
        return
    from brpc_tpu.builtin import flight_recorder as fr
    from brpc_tpu.builtin import services as sv
    from brpc_tpu.rpc import backend_stats as bs
    from brpc_tpu.rpc import span as sm
    from brpc_tpu.transport import device_stats as ds
    _fr_mod, _span_mod, _device_mod, _backend_mod = fr, sm, ds, bs
    _services_mod = sv


class IncidentManager:
    """One instance per process (global_manager()). ``_lock`` is a
    LEAF guarding window state and the artifact ledger; everything
    that can block (recorder control, disk, snapshot builders) runs
    outside it."""

    def __init__(self):
        self._lock = threading.Lock()
        # sampler-tick hot flag: True while a window is armed OR a
        # seal is pending — the ONLY state incident_sample_tick reads
        # before early-outing on a calm tick
        self.window_engaged = False
        self._window_left = 0
        self._incident = None            # the watchdog's Incident object
        self._spool_dir = ""
        self._capture_flipped = False
        self._bundling = False
        self._server_ref = None          # weakref to the serving Server
        # artifact ledger (rebuilt lazily from disk on first read)
        self._artifacts: List[dict] = []
        self._artifact_bytes = 0
        self._scanned_dir = ""
        # lifetime counters (bvars read passively; survive unexpose)
        self.bundled = 0
        self.evicted = 0
        self.skipped = 0                 # open while busy/disabled
        self.last_error = ""

    # ------------------------------------------------------- tick path
    def incident_window_pass(self, opened, closed, t: int) -> None:
        """One tick's incident-window bookkeeping (unique verb name —
        generic names mint false lock-graph edges, the PR 11 lesson).
        Runs on the sampler thread AFTER the watchdog lock released;
        rare by construction (incidents, not requests)."""
        arm = None
        seal = None
        with self._lock:
            if self.window_engaged and self._window_left > 0:
                self._window_left -= 1
                inc = self._incident
                if self._window_left <= 0 or (
                        closed is not None and closed is inc):
                    self._window_left = 0
                    if not self._bundling:
                        self._bundling = True
                        seal = inc
            if opened is not None and not self.window_engaged \
                    and not self._bundling:
                if flag("incident_capture_enabled") \
                        and flag("incident_dir"):
                    self.window_engaged = True
                    self._window_left = max(
                        1, int(flag("incident_window_ticks")))
                    self._incident = opened
                    arm = opened
                else:
                    self.skipped += 1
        if arm is not None:
            self._arm_capture_window(arm)
        if seal is not None:
            th = threading.Thread(
                target=self._bundle_worker, args=(seal,),
                name="incident_bundler", daemon=True)
            th.start()

    def _arm_capture_window(self, inc) -> None:
        """Flip the recorder into corpus-recording mode, spooling into
        a per-incident dir. Sampler thread, outside every lock of
        ours; module-level imports only (sampler-no-lazy-import)."""
        base = str(flag("incident_dir"))
        spool = os.path.join(
            base, f"spool-{inc.id}-{os.getpid()}")
        cfg = _capture.CaptureConfig(
            dir=spool, default_rate=1.0, max_per_second=0,
            rotate_bytes=int(flag("incident_max_artifact_mb")) << 20,
            disk_budget_bytes=int(
                flag("incident_disk_budget_mb")) << 20)
        ok = False
        try:
            ok = _capture.global_recorder().begin_incident_capture(cfg)
        except Exception:
            ok = False
        with self._lock:
            self._spool_dir = spool
            self._capture_flipped = ok

    # --------------------------------------------------- bundler thread
    def _bundle_worker(self, inc) -> None:
        """Everything disk: restore the recorder, read the spool,
        write the artifact, enforce the budget. Own thread — never the
        sampler, never dispatch."""
        try:
            self._bundle_incident(inc)
        except Exception as e:            # never take serving down
            self.last_error = f"{type(e).__name__}: {e}"
        finally:
            with self._lock:
                self._bundling = False
                self.window_engaged = False
                self._incident = None
                self._spool_dir = ""
                self._capture_flipped = False

    def _bundle_incident(self, inc) -> None:
        with self._lock:
            spool = self._spool_dir
            flipped = self._capture_flipped
        if flipped:
            _capture.global_recorder().end_incident_capture(flush_s=3.0)
        base = str(flag("incident_dir"))
        if not base:
            return
        records = []
        if spool and os.path.isdir(spool):
            try:
                records = read_corpus(spool)
            except OSError:
                records = []
        cap_bytes = int(flag("incident_max_artifact_mb")) << 20
        max_records = int(flag("incident_max_corpus_records"))
        doc = self._incident_document(inc, records)
        path = os.path.join(base, f"incident-{inc.id}-{os.getpid()}"
                                  f"-{int(time.time())}{SUFFIX}")
        os.makedirs(base, exist_ok=True)
        w = ArtifactWriter(path)
        truncated = 0
        try:
            w.put_incident_meta(doc)
            for name, snap in self._collect_snapshots(inc):
                if snap is None:
                    continue
                try:
                    w.put_snapshot(name, snap)
                except (TypeError, ValueError, OSError):
                    pass
            for i, rec in enumerate(records):
                if i >= max_records or w.bytes >= cap_bytes:
                    truncated = len(records) - i
                    break
                w.put_request(rec)
        finally:
            w.close()
        if truncated:
            # the sidecar records the truth; re-stamp the meta doc via
            # sidecar only (rewriting the recordio meta record would
            # mean rebuilding the file)
            try:
                with open(path + ".idx", encoding="utf-8") as f:
                    idx = json.load(f)
                idx["corpus_truncated"] = truncated
                with open(path + ".idx", "w", encoding="utf-8") as f:
                    json.dump(idx, f)
            except (OSError, ValueError):
                pass
        if spool:
            shutil.rmtree(spool, ignore_errors=True)
        self._enforce_disk_budget(base, keep=path)
        with self._lock:
            self.bundled += 1
            self._refresh_ledger_locked(base)   # bundler thread: disk ok
        nbundled.add(1)

    def _incident_document(self, inc, records) -> dict:
        classes = {}
        for rec in records:
            if rec.status:
                name = errno_name(rec.status)
                classes[name] = classes.get(name, 0) + 1
        d = inc.to_dict()
        d.update({
            "v": 1, "pid": os.getpid(),
            "created_wall": time.time(),
            "window_ticks": int(flag("incident_window_ticks")),
            "error_classes": classes,
            "corpus_records_total": len(records),
        })
        return d

    def _collect_snapshots(self, inc):
        """Yield (name, payload) pairs, each builder best-effort — a
        broken snapshot must not cost the artifact."""
        sv, fr, sm = _services_mod, _fr_mod, _span_mod
        ds, bs = _device_mod, _backend_mod
        server = self._server_ref() if self._server_ref else None
        if sv is not None and server is not None:
            try:
                yield "status", sv.status_page(server)
            except Exception:
                yield "status", None
        if sv is not None:
            try:
                names = list(inc.keys) or None
                yield "timeline", sv.timeline_page_payload(
                    None, names=names)
            except Exception:
                yield "timeline", None
        if fr is not None:
            try:
                yield "hotspots", fr.global_recorder().dump_state()
            except Exception:
                yield "hotspots", None
        if ds is not None:
            try:
                yield "device", ds.device_page_payload(server)
            except Exception:
                yield "device", None
        if bs is not None:
            try:
                yield "backends", bs.backends_page_payload()
            except Exception:
                yield "backends", None
        # serving flight deck: the /serving payload (batcher + engine +
        # per-method stage panes) joins the bundle whenever the serving
        # lane is loaded — resolved through sys.modules (a read, never
        # an import on the bundler thread), so a TTFT break's artifact
        # carries the step ring that explains it
        srv = sys.modules.get("brpc_tpu.serving.service")
        if srv is not None and server is not None:
            try:
                yield "serving", srv.serving_page_payload(server)
            except Exception:
                yield "serving", None
        if sm is not None:
            try:
                label = f"incident #{inc.id}"
                rows = []
                for span in reversed(
                        sm.global_collector.recent(256)):
                    if any(label in t for _, t in span.annotations):
                        rows.append(span.to_dict())
                        if len(rows) >= _SPAN_BUNDLE_MAX:
                            break
                yield "spans", rows
            except Exception:
                yield "spans", None

    def _enforce_disk_budget(self, base: str, keep: str = "") -> None:
        """Oldest artifacts evicted first; the just-written one is
        never evicted (newest survives even when it alone exceeds the
        budget — a budget that deletes the only evidence is no
        budget)."""
        budget = int(flag("incident_disk_budget_mb")) << 20
        try:
            entries = []
            for p in artifact_files(base):
                try:
                    entries.append((p, os.stat(p).st_size))
                except OSError:
                    pass
            total = sum(sz for _, sz in entries)
            for p, sz in entries:
                if total <= budget:
                    break
                if p == keep:
                    continue
                try:
                    os.remove(p)
                except OSError:
                    continue
                try:
                    os.remove(p + ".idx")
                except OSError:
                    pass
                total -= sz
                with self._lock:
                    self.evicted += 1
        except OSError:
            pass

    # ----------------------------------------------------------- reads
    def _refresh_ledger_locked(self, base: str) -> None:
        # caller holds self._lock; artifact_summary reads sidecars
        # (O(1) per artifact) — acceptable under a leaf on a page read
        rows = []
        total = 0
        for p in artifact_files(base):
            s = artifact_summary(p)
            size = s.get("file_size") or 0
            total += max(0, size)
            rows.append({
                "path": p, "bytes": size,
                "incident_id": s.get("incident_id"),
                "peak_key": s.get("peak_key"),
                "keys": s.get("keys"),
                "opened_t": s.get("opened_t"),
                "corpus_records": s.get("corpus_records"),
                "snapshots": s.get("snapshots"),
            })
        self._artifacts = rows
        self._artifact_bytes = total
        self._scanned_dir = base

    def artifact_rows(self) -> List[dict]:
        """Page-read path: rescans the artifact dir when it changed
        (never called from the sampler thread)."""
        base = str(flag("incident_dir"))
        with self._lock:
            if not base:
                return []
            if self._scanned_dir != base:
                self._refresh_ledger_locked(base)
            return [dict(r) for r in self._artifacts]

    def prime_artifact_ledger(self) -> None:
        """Caller-thread scan (Server.start): artifacts surviving a
        restart show up in the bvars without waiting for a page read."""
        base = str(flag("incident_dir"))
        if not base:
            return
        with self._lock:
            if self._scanned_dir != base:
                self._refresh_ledger_locked(base)

    def artifact_bytes_cached(self) -> int:
        """Sampler-safe: one int read, no lock, no disk — the
        incident_artifact_bytes bvar is sampled on the series tick."""
        return self._artifact_bytes

    def window_open_now(self) -> int:
        return 1 if self.window_engaged else 0

    def incidents_state_payload(self) -> dict:
        """The /incidents page body (local, single process); the
        supervisor serves ShardAggregator.merged_incidents instead."""
        rows = self.artifact_rows()
        with self._lock:
            inc = self._incident
            out = {
                "enabled": bool(flag("incident_capture_enabled")),
                "dir": str(flag("incident_dir")),
                "window_ticks": int(flag("incident_window_ticks")),
                "max_artifact_mb": int(flag("incident_max_artifact_mb")),
                "disk_budget_mb": int(flag("incident_disk_budget_mb")),
                "open": 1 if self.window_engaged else 0,
                "window_left": self._window_left,
                "bundling": self._bundling,
                "capturing": self._capture_flipped,
                "active_incident": inc.to_dict()
                if inc is not None else None,
                "total": self.bundled,
                "evicted": self.evicted,
                "skipped": self.skipped,
                "artifact_bytes": self._artifact_bytes,
                "last_error": self.last_error,
                "pid": os.getpid(),
            }
        out["artifacts"] = rows
        return out

    def attach_serving_server(self, server) -> None:
        self._server_ref = weakref.ref(server)


# ------------------------------------------------------------ singleton

_manager = IncidentManager()


def global_manager() -> IncidentManager:
    return _manager


def incident_sample_tick(opened, closed, t: int) -> None:
    """The watchdog's per-tick hand-off (bvar/anomaly.py), marker-named
    so the sampler-no-lazy-import rule roots its closure here. Idle
    early-out is ONE attribute check."""
    m = _manager
    if opened is None and closed is None and not m.window_engaged:
        return
    m.incident_window_pass(opened, closed, t)


def attach_incident_server(server) -> None:
    """Server.start hook: the bundler's /status snapshot needs the
    serving Server (held weakly — the manager must not keep a stopped
    server alive), and artifacts surviving a restart are primed into
    the ledger here, on the caller thread."""
    _manager.attach_serving_server(server)
    _manager.prime_artifact_ledger()


def incidents_snapshot_payload(server=None) -> dict:
    """ONE builder for the /incidents page: HTTP handler, builtin-RPC
    twin and the shard dump all call this."""
    return _manager.incidents_state_payload()


def incident_status_line() -> dict:
    """The /status page's incidents line (cached bytes — /status must
    stay cheap; /incidents does the authoritative scan)."""
    m = _manager
    return {"open": m.window_open_now(), "total": m.bundled,
            "artifact_bytes": m.artifact_bytes_cached(),
            "url": "/incidents"}


# /vars: exposed at import, RE-exposed by expose_incident_vars at every
# Server.start (the PR 2 unexpose_all survival rule). Passives read the
# live singleton so a postfork replacement is picked up transparently.
nbundled = Adder().expose("incident_total")
_open_var = PassiveStatus(
    lambda: _manager.window_open_now()).expose("incident_open")
_bytes_var = PassiveStatus(
    lambda: _manager.artifact_bytes_cached()).expose(
        "incident_artifact_bytes")


def expose_incident_vars() -> None:
    """Re-expose the incident bvars after an unexpose_all (test
    harnesses between Server.start calls)."""
    nbundled.expose("incident_total")
    _open_var.expose("incident_open")
    _bytes_var.expose("incident_artifact_bytes")


def _postfork_reset() -> None:
    """Fork hygiene: the window, spool and ledger describe the PARENT;
    a shard child starts idle with a fresh leaf lock (the parent's may
    be mid-hold at fork time). Lifetime counters restart — the bvar
    Adder is reset by bvar's own postfork pass."""
    global _manager
    _manager = IncidentManager()


postfork.register("incident.manager", _postfork_reset)
