"""graftlint core: source model, waivers, rule base, analyzer.

The analyzer parses every Python module once into an AST and exposes a
light line-oriented view of the native C++ sources; rules see the whole
file set at once (cross-module checks like lock-order cycles and
registry completeness need it). Findings carry a stable (path, line,
rule) identity so waivers and diffs are deterministic.

Waiver syntax (Python ``#`` and C++ ``//`` comments, same grammar):

    # graftlint: disable=<rule>[,<rule>...] -- <reason>

placed on the offending line or the line directly above. A whole file
opts out of a rule with ``disable-file=``. A waiver MUST carry a
reason after ``--``; a bare waiver is itself reported (rule
``waiver-reason``) so suppressions stay auditable. A reason started on
a comment-only waiver line may wrap across the comment block below it;
the whole run is recorded as the reason.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

_WAIVER_RE = re.compile(
    r"(?:#|//)\s*graftlint:\s*(disable(?:-file)?)="
    r"([A-Za-z0-9_,-]+)\s*(?:--\s*(\S.*))?")


class Finding:
    """One rule violation at a source location."""

    __slots__ = ("rule", "path", "line", "message", "waived", "reason")

    def __init__(self, rule: str, path: str, line: int, message: str):
        self.rule = rule
        self.path = path
        self.line = line
        self.message = message
        self.waived = False
        self.reason: Optional[str] = None   # waiver reason when waived

    def key(self) -> Tuple[str, int, str]:
        return (self.path, self.line, self.rule)

    def format(self) -> str:
        tag = " (waived)" if self.waived else ""
        return f"{self.path}:{self.line}: [{self.rule}]{tag} {self.message}"

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message, "waived": self.waived,
                "reason": self.reason}


class SourceFile:
    """One analyzed file: text + lines + (for .py) a parsed AST, plus
    the waiver table extracted from its comments."""

    def __init__(self, path: str, relpath: str, text: str):
        self.path = path
        self.relpath = relpath
        self.text = text
        self.lines = text.splitlines()
        self.is_python = relpath.endswith(".py")
        self.tree: Optional[ast.AST] = None
        self.parse_error: Optional[str] = None
        if self.is_python:
            try:
                self.tree = ast.parse(text, filename=relpath)
            except SyntaxError as e:
                self.parse_error = f"syntax error: {e}"
        # line -> set of disabled rules; 0 -> file-wide
        self.waivers: Dict[int, set] = {}
        # (line, rule) -> the waiver's full reason text
        self.reasons: Dict[Tuple[int, str], str] = {}
        self.bare_waivers: List[int] = []   # waiver lines missing a reason
        self._scan_waivers()

    def _scan_waivers(self) -> None:
        for i, line in enumerate(self.lines, start=1):
            m = _WAIVER_RE.search(line)
            if not m:
                continue
            kind, rules, reason = m.group(1), m.group(2), m.group(3)
            names = {r.strip() for r in rules.split(",") if r.strip()}
            slots = [0] if kind == "disable-file" else self._slots_for(i)
            if reason:
                reason = self._extend_reason(i, reason)
            for slot in slots:
                self.waivers.setdefault(slot, set()).update(names)
                for name in names:
                    self.reasons[(slot, name)] = reason or ""
            if not reason:
                self.bare_waivers.append(i)

    def _extend_reason(self, i: int, reason: str) -> str:
        """A reason started on a pure-comment waiver line continues
        through the comment run below it (up to the next code line or
        the next waiver) — the audit ledger must record the whole
        sentence, not the first line's fragment."""
        if not self.lines[i - 1].lstrip().startswith(("#", "//")):
            return reason          # inline waiver: reason ends with it
        parts = [reason]
        for j in range(i + 1, min(i + 8, len(self.lines)) + 1):
            nxt = self.lines[j - 1].lstrip()
            if not nxt.startswith(("#", "//")) or "graftlint:" in nxt:
                break
            parts.append(nxt.lstrip("#/").strip())
        return " ".join(p for p in parts if p)

    def _slots_for(self, i: int) -> list:
        """A waiver on line i covers i itself and — when i is a pure
        comment line — the first code line of the run below it (a
        multi-line comment block above the offending statement)."""
        slots = [i]
        stripped = self.lines[i - 1].lstrip()
        if stripped.startswith(("#", "//")):
            j = i + 1
            while j <= len(self.lines) and \
                    self.lines[j - 1].lstrip().startswith(("#", "//")):
                j += 1
            if j <= len(self.lines) and j - i <= 8:
                slots.append(j)
        return slots

    def waiver_reason(self, line: int, rule: str) -> Optional[str]:
        """The waiver reason if (line, rule) is waived, else None.
        Checks the line itself and file-wide — comment-above waivers
        were already mapped onto their first code line by _slots_for,
        so probing line-1 here would only let a waiver leak onto an
        unrelated same-rule finding on the following line."""
        for slot in (line, 0):
            disabled = self.waivers.get(slot)
            if disabled and (rule in disabled or "all" in disabled):
                name = rule if rule in disabled else "all"
                return self.reasons.get((slot, name), "")
        return None


class Rule:
    """Base class for graftlint rules.

    ``check(sf, ctx)`` runs per file; ``finalize(ctx)`` runs once after
    every file was seen (cross-module rules accumulate state in check
    and report in finalize). Both return Finding iterables.
    """

    name = "?"
    description = ""

    def check(self, sf: SourceFile, ctx: "Context") -> Iterable[Finding]:
        return ()

    def finalize(self, ctx: "Context") -> Iterable[Finding]:
        return ()


class Context:
    """Shared analysis context: the full file set plus lazily built
    cross-module tables (class hierarchy, import map)."""

    def __init__(self, files: List[SourceFile]):
        self.files = files
        self.by_relpath = {f.relpath: f for f in files}
        self._classes: Optional[Dict[str, Tuple[SourceFile,
                                                ast.ClassDef]]] = None

    # ---------------------------------------------------- class table
    @property
    def classes(self) -> Dict[str, Tuple[SourceFile, ast.ClassDef]]:
        """qualified 'relpath-sans-.py:ClassName' -> (file, node), plus
        a bare-name alias when unambiguous."""
        if self._classes is None:
            table: Dict[str, Tuple[SourceFile, ast.ClassDef]] = {}
            bare: Dict[str, list] = {}
            for sf in self.files:
                if sf.tree is None:
                    continue
                for node in ast.walk(sf.tree):
                    if isinstance(node, ast.ClassDef):
                        table[f"{sf.relpath}:{node.name}"] = (sf, node)
                        bare.setdefault(node.name, []).append((sf, node))
            for name, hits in bare.items():
                if len(hits) == 1 and name not in table:
                    table[name] = hits[0]
            self._classes = table
        return self._classes

    def resolve_class(self, name: str) -> Optional[Tuple[SourceFile,
                                                         ast.ClassDef]]:
        return self.classes.get(name)

    def mro_class_defs(self, sf: SourceFile,
                       node: ast.ClassDef) -> List[Tuple[SourceFile,
                                                         ast.ClassDef]]:
        """(file, ClassDef) for node and every resolvable base,
        breadth-first across the analyzed file set."""
        out, seen, queue = [], set(), [(sf, node)]
        while queue:
            cur_sf, cur = queue.pop(0)
            key = (cur_sf.relpath, cur.name)
            if key in seen:
                continue
            seen.add(key)
            out.append((cur_sf, cur))
            for base in cur.bases:
                base_name = base.id if isinstance(base, ast.Name) else (
                    base.attr if isinstance(base, ast.Attribute) else None)
                if base_name:
                    hit = self.resolve_class(base_name)
                    if hit:
                        queue.append(hit)
        return out


def iter_source_files(paths: Sequence[str]) -> List[SourceFile]:
    """Collect .py and .cc files under the given paths (files or
    directories), relpaths anchored at the repo root (the directory
    containing the brpc_tpu package) when detectable."""
    roots: List[str] = []
    for p in paths:
        roots.append(os.path.abspath(p))
    # anchor: nearest ancestor containing brpc_tpu/ (for stable relpaths)
    anchor = os.getcwd()
    for r in roots:
        d = r if os.path.isdir(r) else os.path.dirname(r)
        while d and d != os.path.dirname(d):
            if os.path.isdir(os.path.join(d, "brpc_tpu")):
                anchor = d
                break
            d = os.path.dirname(d)
    out: List[SourceFile] = []
    seen = set()

    def add(fp: str) -> None:
        if fp in seen or not fp.endswith((".py", ".cc")):
            return
        seen.add(fp)
        try:
            with open(fp, encoding="utf-8", errors="replace") as f:
                text = f.read()
        except OSError:
            return
        rel = os.path.relpath(fp, anchor)
        out.append(SourceFile(fp, rel.replace(os.sep, "/"), text))

    for r in roots:
        if os.path.isfile(r):
            add(r)
            continue
        for dirpath, dirnames, filenames in os.walk(r):
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__", ".git")]
            for fn in sorted(filenames):
                add(os.path.join(dirpath, fn))
    out.sort(key=lambda s: s.relpath)
    return out


class Analyzer:
    def __init__(self, rules: Optional[Sequence[Rule]] = None):
        if rules is None:
            from brpc_tpu.analysis.rules import default_rules
            rules = default_rules()
        self.rules = list(rules)

    def run(self, paths: Sequence[str]) -> Tuple[List[Finding],
                                                 List[Finding]]:
        """Returns (active, waived) findings, each sorted by location.
        Waivers lacking a reason surface as ``waiver-reason`` findings
        (never waivable by themselves)."""
        files = iter_source_files(paths)
        ctx = Context(files)
        findings: List[Finding] = []
        for sf in files:
            if sf.parse_error:
                findings.append(Finding("parse", sf.relpath, 1,
                                        sf.parse_error))
                continue
            for rule in self.rules:
                findings.extend(rule.check(sf, ctx))
        for rule in self.rules:
            findings.extend(rule.finalize(ctx))
        for sf in files:
            for line in sf.bare_waivers:
                findings.append(Finding(
                    "waiver-reason", sf.relpath, line,
                    "waiver without a reason: append ' -- <why>'"))
        active: List[Finding] = []
        waived: List[Finding] = []
        seen = set()
        for f in sorted(findings, key=Finding.key):
            if f.key() in seen:
                continue
            seen.add(f.key())
            sf = ctx.by_relpath.get(f.path)
            reason = (sf.waiver_reason(f.line, f.rule)
                      if sf is not None and f.rule != "waiver-reason"
                      else None)
            if reason is not None:
                f.waived = True
                f.reason = reason
                waived.append(f)
            else:
                active.append(f)
        return active, waived
