"""Whole-program lock model: every lock in the file set, and the
interprocedural acquisition graph over them.

The reference bRPC's concurrency invariants are *graph* properties —
"never take the LB lock while holding the arbitration lock", "never
fire a user callback while any framework lock is held" — and the PR-by-
PR history of this repo (the batcher callbacks of PR 8, the
``_arb_lock``/``_lb_lock`` attempt records of PR 7) is the history of
re-learning them by hand. This module makes the graph a first-class
artifact the rules in ``rules/lock_cycle.py``, ``rules/
callback_under_lock.py`` and ``rules/blocking_under_lock.py`` check,
the snapshot test pins, and ``docs/invariants.md`` publishes.

Model construction:

1. **Lock discovery.** Every ``threading.Lock()`` / ``RLock()`` /
   ``FiberMutex()`` creation is a lock node — ``self._x = ...`` in a
   class gives ``Class._x``, module-level gives ``module:_x``, and the
   lazy-member dict idiom (``Controller._LAZY = {"_arb_lock":
   threading.RLock, ...}``) gives ``Class._key``. Acquisitions of an
   attribute that is unique across all discovered locks resolve to its
   owning class even through a foreign receiver (``with cntl._arb_lock:``
   in another module lands on ``Controller._arb_lock``).
2. **Function summaries.** Every function body is walked once with a
   held-lock stack: ``with`` acquisitions (including multi-item forms),
   manual ``.acquire()`` of a discovered lock, calls made while holding,
   blocking operations, and callback invocations are recorded with the
   held set at that point.
3. **Two-pass call-edge resolution** (the fiber-blocking rule's def-
   table discipline, widened to the whole program): defs are collected
   first so forward and cross-module edges resolve against the COMPLETE
   table — same-module names, ``from x import f`` / ``import x as y``
   imports, ``self.``/MRO methods, light receiver-type inference
   (``self.x = ClassName(...)`` in ``__init__``; locals assigned from a
   constructor), and unique-method fallback for method names defined by
   exactly one class in the set (common verbs blocklisted).
4. **Fixpoints.** ``acquires_closure`` (locks a call may take,
   transitively) feeds held->acquired edges; ``under_locks`` (locks
   possibly held when a function runs) feeds the callback/blocking
   rules, each finding carrying the witness call chain.

The model is built once per analysis context (``get_lock_model``) and
shared by every rule riding it.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from brpc_tpu.analysis.core import Context, SourceFile

# lock-constructor shapes: threading.Lock() / threading.RLock() /
# FiberMutex() (butex-backed; contended fibers suspend, but the HELD
# region still orders against every other lock)
_LOCK_CTORS = {"Lock": "Lock", "RLock": "RLock", "FiberMutex": "FiberMutex"}

# method names too generic for the unique-method fallback: an edge
# guessed through one of these would be noise, not analysis — the set
# covers framework verbs AND the builtin str/bytes/dict/list/set/array
# methods (a `s.replace(...)` must never resolve to some class's
# replace())
_COMMON_METHODS = frozenset((
    "run", "start", "stop", "close", "get", "put", "add", "remove",
    "write", "read", "send", "recv", "wait", "set", "clear", "update",
    "append", "pop", "join", "open", "flush", "reset", "name", "value",
    "copy", "items", "keys", "values", "submit", "cancel", "acquire",
    "release", "register", "main", "call", "connect", "handle", "next",
    "snapshot", "format", "count", "index", "insert", "extend", "expose",
    # builtin-type methods
    "replace", "strip", "lstrip", "rstrip", "split", "rsplit",
    "splitlines", "startswith", "endswith", "encode", "decode",
    "lower", "upper", "title", "ljust", "rjust", "zfill", "find",
    "rfind", "search", "match", "group", "groups", "sub", "fullmatch",
    "sort", "reverse", "setdefault", "discard", "popleft", "popitem",
    "appendleft", "to_bytes", "from_bytes", "hex", "tobytes", "cast",
    "item", "tolist", "astype", "reshape", "fill", "sum", "mean",
    "max", "min", "any", "all", "seek", "tell", "getvalue", "readline",
    "readlines", "fileno", "most_common", "elements", "total",
    "isoformat", "timestamp", "serialize", "parse",
    # threading.Condition verbs: a unique same-named fiber method must
    # not claim a stdlib condvar's notify (ring_lane's _barrier_cv)
    "notify", "notify_all",
))

_SUBPROCESS_BLOCKING = ("run", "call", "check_call", "check_output",
                        "getoutput", "getstatusoutput")

_SOCKETISH = ("sock", "stream", "conn")

# container-method calls that MUTATE their receiver: `self._q.append(x)`
# is a write to `_q` for guard purposes, same as `self._q = ...`
_MUTATORS = frozenset((
    "append", "appendleft", "add", "pop", "popleft", "popitem",
    "update", "extend", "extendleft", "remove", "discard", "clear",
    "insert", "setdefault", "rotate", "sort", "reverse",
))


class LockDef:
    """One discovered lock object."""

    __slots__ = ("name", "relpath", "line", "kind")

    def __init__(self, name: str, relpath: str, line: int, kind: str):
        self.name = name
        self.relpath = relpath
        self.line = line
        self.kind = kind


class CallSite:
    """One call made by a function: the resolution descriptor, the
    locks held at the call, and the location."""

    __slots__ = ("desc", "held", "line")

    def __init__(self, desc: tuple, held: Tuple[str, ...], line: int):
        self.desc = desc
        self.held = held
        self.line = line


class FuncInfo:
    """Summary of one function body."""

    __slots__ = ("key", "relpath", "qual", "cls", "line",
                 "acquires", "with_edges", "calls", "blocking",
                 "callbacks", "resolved_calls", "imports",
                 "thread_targets", "sleeps_in_loop", "attr_uses")

    def __init__(self, key: str, relpath: str, qual: str,
                 cls: Optional[str], line: int):
        self.key = key
        self.relpath = relpath
        self.qual = qual
        self.cls = cls
        self.line = line
        self.acquires: List[Tuple[str, int]] = []
        self.with_edges: List[Tuple[str, str, int]] = []
        self.calls: List[CallSite] = []
        # (line, why, held) blocking ops with the held set at that point
        self.blocking: List[Tuple[int, str, Tuple[str, ...]]] = []
        # (line, desc, held) callback/user-hook invocations
        self.callbacks: List[Tuple[int, str, Tuple[str, ...]]] = []
        self.resolved_calls: List[Tuple[str, Tuple[str, ...], int]] = []
        # import statements executed in this body (lazy imports)
        self.imports: List[Tuple[int, str]] = []
        # threading.Thread(target=...) creations: (desc, name kwarg, line)
        self.thread_targets: List[Tuple[tuple, str, int]] = []
        # time.sleep call lines sitting inside a while-loop body
        self.sleeps_in_loop: List[int] = []
        # attribute/global access sites with the held-lock set at each:
        # (kind 'w'|'r', field key 'Class.attr'|'module:name', line,
        # held) — the guarded-by rule's raw material. Only resolvable
        # receivers are recorded (self.X, typed receivers, declared
        # globals); an access the model cannot attribute to a class is
        # skipped, never guessed
        self.attr_uses: List[Tuple[str, str, int, Tuple[str, ...]]] = []


class _ModuleMaps:
    """Per-module import/alias tables used by call + lock resolution."""

    def __init__(self, sf: SourceFile):
        self.relpath = sf.relpath
        self.modname = sf.relpath[:-3].replace("/", ".")
        self.short = sf.relpath.rsplit("/", 1)[-1][:-3]
        self.mod_aliases: Dict[str, str] = {}     # alias -> dotted module
        self.from_imports: Dict[str, Tuple[str, str]] = {}  # local -> (mod, orig)
        self.time_aliases: Set[str] = set()
        self.subprocess_aliases: Set[str] = set()
        self.socket_aliases: Set[str] = set()
        self.direct_sleep: Set[str] = set()
        self.direct_subprocess: Set[str] = set()
        # names assigned at module top level (mutable module state the
        # guarded-by rule tracks writes/reads of)
        self.module_globals: Set[str] = set()
        for node in sf.tree.body:
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        self.module_globals.add(tgt.id)
            elif isinstance(node, ast.AnnAssign) and \
                    isinstance(node.target, ast.Name):
                self.module_globals.add(node.target.id)
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    alias = a.asname or a.name.split(".")[0]
                    self.mod_aliases[alias] = a.name
                    if a.name == "time":
                        self.time_aliases.add(alias)
                    elif a.name == "subprocess":
                        self.subprocess_aliases.add(alias)
                    elif a.name == "socket":
                        self.socket_aliases.add(alias)
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    local = a.asname or a.name
                    self.from_imports[local] = (node.module, a.name)
                    if node.module == "time" and a.name == "sleep":
                        self.direct_sleep.add(local)
                    if node.module == "subprocess" and \
                            a.name in _SUBPROCESS_BLOCKING:
                        self.direct_subprocess.add(local)


def _ctor_kind(call: ast.AST) -> Optional[str]:
    """'Lock'/'RLock'/'FiberMutex' when the node is a lock constructor
    call; None otherwise."""
    if not isinstance(call, ast.Call):
        return None
    fn = call.func
    if isinstance(fn, ast.Attribute) and fn.attr in _LOCK_CTORS and \
            isinstance(fn.value, ast.Name) and fn.value.id == "threading":
        return _LOCK_CTORS[fn.attr]
    if isinstance(fn, ast.Name) and fn.id in _LOCK_CTORS:
        # bare Lock()/RLock() only counts when imported from threading;
        # FiberMutex() counts bare (it IS the package's own primitive)
        return _LOCK_CTORS[fn.id] if fn.id == "FiberMutex" else None
    return None


def _ctor_ref_kind(node: ast.AST) -> Optional[str]:
    """The lazy-dict form: a REFERENCE to threading.Lock/RLock (not a
    call), as in Controller._LAZY values."""
    if isinstance(node, ast.Attribute) and node.attr in ("Lock", "RLock") \
            and isinstance(node.value, ast.Name) \
            and node.value.id == "threading":
        return node.attr
    if isinstance(node, ast.Name) and node.id == "FiberMutex":
        return "FiberMutex"
    return None


class LockModel:
    def __init__(self, ctx: Context):
        self.ctx = ctx
        self.locks: Dict[str, LockDef] = {}
        # lock attr name -> [lock qualified names] (for unique-attr
        # resolution of foreign receivers)
        self._by_attr: Dict[str, List[str]] = {}
        self.funcs: Dict[str, FuncInfo] = {}
        # (modname, qual) -> fkey;  bare function name -> [fkey]
        self._def_index: Dict[Tuple[str, str], str] = {}
        self._methods: Dict[str, List[str]] = {}   # meth name -> [fkey]
        self._class_methods: Dict[str, Dict[str, str]] = {}
        self._maps: Dict[str, _ModuleMaps] = {}
        # fkey -> ClassName from the def's return annotation: resolves
        # factory-call receivers (global_dispatcher().pause_read(...))
        # that the unique-method fallback loses once two lane classes
        # define the method
        self._ret_types: Dict[str, str] = {}
        # (class, attr) -> ClassName   |   (modname, var) -> ClassName
        self._attr_types: Dict[Tuple[str, str], str] = {}
        self._var_types: Dict[Tuple[str, str], str] = {}
        self._event_attrs: Set[Tuple[str, str]] = set()  # (cls, attr)
        # threading.Condition attributes/globals: a Condition IS a
        # mutex for guarded-by purposes, but it never joins the lock
        # graph (its with-regions are tracked on a separate stack so
        # the pinned edge set and the blocking/callback rules are
        # unaffected)
        self._cond_attrs: Set[Tuple[str, str]] = set()   # (cls, attr)
        self._cond_vars: Set[Tuple[str, str]] = set()    # (mod, name)
        # edges: (a, b) -> (relpath, line, chain) first witness
        self.edges: Dict[Tuple[str, str],
                         Tuple[str, int, Tuple[str, ...]]] = {}
        # locks each function may acquire, transitively
        self.acquires_closure: Dict[str, Set[str]] = {}
        # locks possibly held when the function runs (callers' holds)
        self.under_locks: Dict[str, Set[str]] = {}
        # under_locks witness: fkey -> (caller fkey, lock, line)
        self._under_witness: Dict[str, Tuple[str, str, int]] = {}
        self._build()

    # ------------------------------------------------------------ build
    def _py_files(self) -> List[SourceFile]:
        return [sf for sf in self.ctx.files
                if sf.is_python and sf.tree is not None
                and "/analysis/" not in sf.relpath]

    def _build(self) -> None:
        files = self._py_files()
        for sf in files:
            self._maps[sf.relpath] = _ModuleMaps(sf)
        for sf in files:
            self._discover_locks(sf)
            self._collect_defs(sf)
        for name in self.locks:
            attr = name.split(".")[-1] if "." in name else \
                name.split(":")[-1]
            self._by_attr.setdefault(attr, []).append(name)
        # pass 2: summaries against the COMPLETE def/lock tables —
        # helpers below their callers and cross-module callees resolve
        for sf in files:
            self._summarize(sf)
        self._resolve_calls()
        self._fixpoint()
        # resolved thread targets: (creator, target fkey, name, line)
        self.thread_roots: List[Tuple[FuncInfo, str, str, int]] = []
        for info in self.funcs.values():
            maps = self._maps[info.relpath]
            for desc, tname, line in info.thread_targets:
                fkey = self.resolve_call(desc, maps, info.cls)
                if fkey:
                    self.thread_roots.append((info, fkey, tname, line))

    # ------------------------------------------------- lock discovery
    def _discover_locks(self, sf: SourceFile) -> None:
        maps = self._maps[sf.relpath]
        short = maps.short

        def add(name: str, line: int, kind: str) -> None:
            if name not in self.locks:
                self.locks[name] = LockDef(name, sf.relpath, line, kind)

        class V(ast.NodeVisitor):
            def __init__(v):
                v.cls: List[str] = []

            def visit_ClassDef(v, node: ast.ClassDef):
                v.cls.append(node.name)
                for child in node.body:
                    v.visit(child)
                v.cls.pop()

            def visit_Assign(v, node: ast.Assign):
                kind = _ctor_kind(node.value)
                if kind:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Attribute) and \
                                isinstance(tgt.value, ast.Name) and \
                                tgt.value.id == "self" and v.cls:
                            add(f"{v.cls[-1]}.{tgt.attr}",
                                node.lineno, kind)
                        elif isinstance(tgt, ast.Name):
                            if v.cls:
                                add(f"{v.cls[-1]}.{tgt.id}",
                                    node.lineno, kind)
                            else:
                                add(f"{short}:{tgt.id}", node.lineno, kind)
                elif isinstance(node.value, ast.Dict) and v.cls:
                    # the lazy-member dict idiom (Controller._LAZY)
                    for k, val in zip(node.value.keys, node.value.values):
                        rkind = _ctor_ref_kind(val)
                        if rkind and isinstance(k, ast.Constant) and \
                                isinstance(k.value, str):
                            add(f"{v.cls[-1]}.{k.value}",
                                val.lineno, rkind)
                # receiver-type + event inference piggybacks this walk
                self_note(node, v.cls)
                v.generic_visit(node)

        def self_note(node: ast.Assign, cls: List[str]) -> None:
            val = node.value
            if not isinstance(val, ast.Call):
                return
            # fluent chains (`Adder().expose("name")` returns the
            # Adder): unwrap to the constructor call so the bound
            # name still gets its receiver type
            while isinstance(val.func, ast.Attribute) and \
                    isinstance(val.func.value, ast.Call):
                val = val.func.value
            fn = val.func
            cls_name = None
            if isinstance(fn, ast.Name):
                cls_name = fn.id
            elif isinstance(fn, ast.Attribute):
                cls_name = fn.attr
            if cls_name is None:
                return
            is_threading = (isinstance(fn, ast.Attribute)
                            and isinstance(fn.value, ast.Name)
                            and fn.value.id == "threading")
            is_event = cls_name == "Event" and is_threading
            is_cond = cls_name == "Condition" and is_threading
            for tgt in node.targets:
                if isinstance(tgt, ast.Attribute) and \
                        isinstance(tgt.value, ast.Name) and \
                        tgt.value.id == "self" and cls:
                    if is_event:
                        self._event_attrs.add((cls[-1], tgt.attr))
                    elif is_cond:
                        self._cond_attrs.add((cls[-1], tgt.attr))
                    elif cls_name in self.ctx.classes:
                        self._attr_types[(cls[-1], tgt.attr)] = cls_name
                elif isinstance(tgt, ast.Name) and not cls:
                    if is_cond:
                        self._cond_vars.add((maps.modname, tgt.id))
                    elif cls_name in self.ctx.classes and not is_event:
                        self._var_types[(maps.modname, tgt.id)] = cls_name

        V().visit(sf.tree)

    # ---------------------------------------------------- def indexing
    def _collect_defs(self, sf: SourceFile) -> None:
        maps = self._maps[sf.relpath]

        def enter(node, cls: Optional[str]) -> None:
            qual = f"{cls}.{node.name}" if cls else node.name
            fkey = f"{maps.modname}::{qual}"
            self.funcs[fkey] = FuncInfo(fkey, sf.relpath, qual, cls,
                                        node.lineno)
            self._def_index[(maps.modname, qual)] = fkey
            ann = getattr(node, "returns", None)
            if isinstance(ann, ast.Subscript):
                # Optional[X]: the class inside
                v = ann.value
                vn = v.id if isinstance(v, ast.Name) else (
                    v.attr if isinstance(v, ast.Attribute) else None)
                if vn == "Optional":
                    ann = ann.slice
            if isinstance(ann, ast.BinOp):
                # PEP-604 "X | None" / "None | X": the non-None side
                if isinstance(ann.right, ast.Constant) and \
                        ann.right.value is None:
                    ann = ann.left
                elif isinstance(ann.left, ast.Constant) and \
                        ann.left.value is None:
                    ann = ann.right
            nm = None
            if isinstance(ann, ast.Name):
                nm = ann.id
            elif isinstance(ann, ast.Attribute):
                nm = ann.attr
            elif isinstance(ann, ast.Constant) and \
                    isinstance(ann.value, str):
                # string annotation, possibly "mod.X | None": first
                # Capitalized non-None union member
                for part in ann.value.split("|"):
                    part = part.split(".")[-1].strip().strip("'\"")
                    if part and part != "None" and part[0].isupper():
                        nm = part
                        break
            if nm and nm[:1].isupper() and nm != "None":
                self._ret_types[fkey] = nm
            if cls:
                self._methods.setdefault(node.name, []).append(fkey)
                self._class_methods.setdefault(cls, {})[node.name] = fkey
            else:
                self._methods.setdefault(node.name, []).append(fkey)

        for node in sf.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                enter(node, None)
            elif isinstance(node, ast.ClassDef):
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        enter(item, node.name)

    # -------------------------------------------------- lock resolution
    def lock_at(self, node: ast.AST, maps: _ModuleMaps,
                cls: Optional[str]) -> Optional[str]:
        """Resolve an acquisition expression to a lock node name, or
        None when the expression is not a known/lock-like object."""
        if isinstance(node, ast.Attribute):
            attr = node.attr
            base = node.value
            if isinstance(base, ast.Name) and base.id == "self" and cls:
                name = f"{cls}.{attr}"
                if name in self.locks:
                    return name
                # inherited lock: find the defining base class
                for cand in self._mro_lock(cls, attr):
                    return cand
                if "lock" in attr.lower() or "mutex" in attr.lower():
                    return name          # unknown but lock-like
                return None
            # foreign receiver: typed receiver, then unique attr
            rtype = self._receiver_type(base, maps, cls)
            if rtype:
                name = f"{rtype}.{attr}"
                if name in self.locks:
                    return name
                for cand in self._mro_lock(rtype, attr):
                    return cand
            owners = self._by_attr.get(attr, ())
            if len(owners) == 1:
                return owners[0]
            if "lock" in attr.lower() or "mutex" in attr.lower():
                recv = base.id if isinstance(base, ast.Name) else "?"
                return f"{maps.short}:{recv}.{attr}"
            return None
        if isinstance(node, ast.Name):
            name = f"{maps.short}:{node.id}"
            if name in self.locks:
                return name
            if node.id in self.from_imported_locks(maps):
                return self.from_imported_locks(maps)[node.id]
            if "lock" in node.id.lower() or "mutex" in node.id.lower():
                return name
        return None

    def _mro_lock(self, cls: str, attr: str) -> Iterable[str]:
        hit = self.ctx.resolve_class(cls)
        if hit is None:
            return
        for _, c in self.ctx.mro_class_defs(*hit):
            name = f"{c.name}.{attr}"
            if name in self.locks:
                yield name
                return

    def from_imported_locks(self, maps: _ModuleMaps) -> Dict[str, str]:
        out = {}
        for local, (mod, orig) in maps.from_imports.items():
            short = mod.rsplit(".", 1)[-1]
            name = f"{short}:{orig}"
            if name in self.locks:
                out[local] = name
        return out

    def _receiver_type(self, base: ast.AST, maps: _ModuleMaps,
                       cls: Optional[str]) -> Optional[str]:
        if isinstance(base, ast.Name):
            t = self._var_types.get((maps.modname, base.id))
            if t:
                return t
        if isinstance(base, ast.Attribute) and \
                isinstance(base.value, ast.Name) and \
                base.value.id == "self" and cls:
            return self._attr_types.get((cls, base.attr))
        return None

    # ------------------------------------------------------- summaries
    def _summarize(self, sf: SourceFile) -> None:
        maps = self._maps[sf.relpath]
        model = self

        def walk_func(fkey: str, cls: Optional[str], node) -> None:
            info = self.funcs[fkey]
            _FuncWalk(model, maps, info, cls).walk(node)

        for node in sf.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                walk_func(f"{maps.modname}::{node.name}", None, node)
            elif isinstance(node, ast.ClassDef):
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        walk_func(f"{maps.modname}::{node.name}."
                                  f"{item.name}", node.name, item)

    # -------------------------------------------------- call resolution
    def resolve_call(self, desc: tuple, maps: _ModuleMaps,
                     cls: Optional[str]) -> Optional[str]:
        """Resolve a call descriptor recorded by _FuncWalk to a fkey."""
        kind = desc[0]
        if kind == "bare":
            name = desc[1]
            fkey = self._def_index.get((maps.modname, name))
            if fkey:
                return fkey
            fi = maps.from_imports.get(name)
            if fi:
                mod, orig = fi
                fkey = self._def_index.get((mod, orig))
                if fkey:
                    return fkey
            return None
        if kind == "self":
            meth = desc[1]
            if cls:
                fkey = self._class_lookup(cls, meth)
                if fkey:
                    return fkey
            return None
        if kind == "super":
            # the overridden method: first definer in the MRO past cls
            meth = desc[1]
            if not cls:
                return None
            hit = self.ctx.resolve_class(cls)
            if hit is None:
                return None
            for _, c in self.ctx.mro_class_defs(*hit):
                if c.name == cls:
                    continue
                fkey = self._class_methods.get(c.name, {}).get(meth)
                if fkey:
                    return fkey
            return None
        if kind == "attr":
            recv_desc, meth = desc[1], desc[2]
            # module alias: mod.func()
            if recv_desc[0] == "name":
                rn = recv_desc[1]
                mod = maps.mod_aliases.get(rn)
                if mod:
                    return self._def_index.get((mod, meth))
                # from-imported class: ClassName.meth()
                fi = maps.from_imports.get(rn)
                if fi and fi[1] in self._class_methods:
                    return self._class_lookup(fi[1], meth)
                if rn in self._class_methods:
                    return self._class_lookup(rn, meth)
                t = self._var_types.get((maps.modname, rn))
                if t:
                    return self._class_lookup(t, meth)
            elif recv_desc[0] == "selfattr" and cls:
                t = self._attr_types.get((cls, recv_desc[1]))
                if t:
                    fkey = self._class_lookup(t, meth)
                    if fkey:
                        return fkey
            elif recv_desc[0] == "callret":
                # the receiver is a factory call: type it from the
                # factory's return annotation (global_dispatcher() ->
                # EventDispatcher), so lane-duck-typed methods resolve
                # even when several classes define them
                fname = recv_desc[1]
                ffkey = self._def_index.get((maps.modname, fname))
                if not ffkey:
                    fi = maps.from_imports.get(fname)
                    if fi:
                        ffkey = self._def_index.get((fi[0], fi[1]))
                if ffkey:
                    rt = self._ret_types.get(ffkey)
                    if rt:
                        fkey = self._class_lookup(rt, meth)
                        if fkey:
                            return fkey
            # unique-method fallback
            if meth not in _COMMON_METHODS and not meth.startswith("__"):
                hits = self._methods.get(meth, ())
                cm = [h for h in hits if self.funcs[h].cls]
                if len(cm) == 1:
                    return cm[0]
        return None

    def _class_lookup(self, cls: str, meth: str) -> Optional[str]:
        direct = self._class_methods.get(cls, {}).get(meth)
        if direct:
            return direct
        hit = self.ctx.resolve_class(cls)
        if hit is None:
            return None
        for _, c in self.ctx.mro_class_defs(*hit):
            fkey = self._class_methods.get(c.name, {}).get(meth)
            if fkey:
                return fkey
        return None

    def _resolve_calls(self) -> None:
        for info in self.funcs.values():
            maps = self._maps[info.relpath]
            for site in info.calls:
                fkey = self.resolve_call(site.desc, maps, info.cls)
                if fkey and fkey != info.key:
                    info.resolved_calls.append((fkey, site.held,
                                                site.line))

    # --------------------------------------------------------- fixpoint
    def _fixpoint(self) -> None:
        # 1. transitive acquires
        reach = {k: {a for a, _ in f.acquires}
                 for k, f in self.funcs.items()}
        changed = True
        while changed:
            changed = False
            for k, f in self.funcs.items():
                for callee, _, _ in f.resolved_calls:
                    extra = reach.get(callee, set()) - reach[k]
                    if extra:
                        reach[k].update(extra)
                        changed = True
        self.acquires_closure = reach
        # 2. edges: direct with-nesting + held-at-call -> callee closure
        for f in self.funcs.values():
            for a, b, line in f.with_edges:
                self.edges.setdefault((a, b), (f.relpath, line, (f.key,)))
            for callee, held, line in f.resolved_calls:
                if not held:
                    continue
                for b in reach.get(callee, ()):
                    for a in held:
                        if a != b:
                            self.edges.setdefault(
                                (a, b),
                                (f.relpath, line, (f.key, callee)))
        # 3. under_locks: locks possibly held when a function runs
        under: Dict[str, Set[str]] = {k: set() for k in self.funcs}
        changed = True
        while changed:
            changed = False
            for k, f in self.funcs.items():
                for callee, held, line in f.resolved_calls:
                    if callee not in under:
                        continue
                    inbound = set(held) | under[k]
                    extra = inbound - under[callee]
                    if extra:
                        under[callee].update(extra)
                        self._under_witness.setdefault(
                            callee, (k, next(iter(extra)), line))
                        changed = True
        self.under_locks = under

    # -------------------------------------------------------- reporting
    def same_module_closure(self, root: str):
        """BFS over resolved call edges restricted to the root's own
        module, yielding ``(FuncInfo, chain)`` once per function — the
        traversal the thread-loop rules (sampler imports, sleep
        pacing) share."""
        stack = [(root, (root,))]
        seen: Set[str] = set()
        while stack:
            key, chain = stack.pop()
            if key in seen:
                continue
            seen.add(key)
            info = self.funcs.get(key)
            if info is None:
                continue
            yield info, chain
            for callee, _, _ in info.resolved_calls:
                if callee in self.funcs and \
                        self.funcs[callee].relpath == info.relpath:
                    stack.append((callee, chain + (callee,)))

    def witness_chain(self, fkey: str, limit: int = 6) -> List[str]:
        """Caller chain showing how fkey comes to run under a lock."""
        chain = [fkey]
        seen = {fkey}
        cur = fkey
        while cur in self._under_witness and len(chain) < limit:
            caller, _, _ = self._under_witness[cur]
            if caller in seen:
                break
            chain.append(caller)
            seen.add(caller)
            cur = caller
        return list(reversed(chain))

    def acquire_site(self, fkey: str,
                     lock: str) -> Optional[Tuple[str, int]]:
        """Where (relpath, line) the function or its callees first
        acquire the given lock — BFS so the witness is shortest."""
        queue = [fkey]
        seen = set()
        while queue:
            cur = queue.pop(0)
            if cur in seen:
                continue
            seen.add(cur)
            f = self.funcs.get(cur)
            if f is None:
                continue
            for a, line in f.acquires:
                if a == lock:
                    return (f.relpath, line)
            for callee, _, _ in f.resolved_calls:
                queue.append(callee)
        return None

    def graph(self) -> Dict[str, Set[str]]:
        g: Dict[str, Set[str]] = {}
        for (a, b) in self.edges:
            g.setdefault(a, set()).add(b)
            g.setdefault(b, set())
        return g

    def cycles(self) -> List[Tuple[str, ...]]:
        """Elementary cycles via Tarjan SCCs (every SCC with an internal
        edge reports one canonical cycle)."""
        graph = self.graph()
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on_stack: Set[str] = set()
        stack: List[str] = []
        counter = [0]
        sccs: List[List[str]] = []

        def strongconnect(v: str) -> None:
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on_stack.add(v)
            for w in sorted(graph.get(v, ())):
                if w not in index:
                    strongconnect(w)
                    low[v] = min(low[v], low[w])
                elif w in on_stack:
                    low[v] = min(low[v], index[w])
            if low[v] == index[v]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == v:
                        break
                sccs.append(scc)

        for v in sorted(graph):
            if v not in index:
                strongconnect(v)
        out: List[Tuple[str, ...]] = []
        for scc in sccs:
            if len(scc) > 1:
                out.append(tuple(sorted(scc)))
            elif scc and scc[0] in graph.get(scc[0], ()):
                out.append((scc[0],))
        return out


class _FuncWalk(ast.NodeVisitor):
    """One function body: held-lock stack + event recording."""

    def __init__(self, model: LockModel, maps: _ModuleMaps,
                 info: FuncInfo, cls: Optional[str]):
        self.model = model
        self.maps = maps
        self.info = info
        self.cls = cls
        self.held: List[str] = []
        # Condition-guarded regions: a parallel stack feeding ONLY the
        # attr_uses held tuples (conditions are mutexes for guard
        # inference but stay out of the lock graph / blocking rules)
        self.cond_held: List[str] = []
        self.loops = 0                    # while-loop nesting depth
        self.awaited: Set[int] = set()
        self.local_events: Set[str] = set()
        self.local_sockets: Set[str] = set()
        self.with_ctxs: Set[str] = set()   # receivers used as `with X:`
        self.globals_decl: Set[str] = set()   # `global x` names
        self.local_stores: Set[str] = set()   # names assigned locally
        # Attribute/Name nodes that are WRITES despite Load ctx (the
        # receiver of a subscript store / del / mutating method call)
        self._sub_writes: Set[int] = set()
        # Attribute nodes that are a call's method slot, not field reads
        self._method_attrs: Set[int] = set()

    def walk(self, func) -> None:
        for node in ast.walk(func):
            if isinstance(node, ast.Await) and \
                    isinstance(node.value, ast.Call):
                self.awaited.add(id(node.value))
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    r = _recv_name(item.context_expr)
                    if r:
                        self.with_ctxs.add(r)
            if isinstance(node, ast.Global):
                self.globals_decl.update(node.names)
            if isinstance(node, ast.Name) and \
                    isinstance(node.ctx, ast.Store):
                self.local_stores.add(node.id)
        self.local_stores -= self.globals_decl
        for child in func.body:
            self.visit(child)

    # nested defs are separate contexts (and lambdas defer execution)
    def visit_FunctionDef(self, node):
        pass

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        pass

    def visit_Import(self, node: ast.Import) -> None:
        names = ", ".join(a.name for a in node.names)
        self.info.imports.append((node.lineno, names))

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        names = ", ".join(a.name for a in node.names)
        self.info.imports.append(
            (node.lineno, f"{node.module or '.'}: {names}"))

    def visit_While(self, node: ast.While) -> None:
        self.loops += 1
        self.generic_visit(node)
        self.loops -= 1

    def _cond_name(self, expr: ast.AST) -> Optional[str]:
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id == "self" and self.cls and \
                (self.cls, expr.attr) in self.model._cond_attrs:
            return f"{self.cls}.{expr.attr}"
        if isinstance(expr, ast.Name) and \
                (self.maps.modname, expr.id) in self.model._cond_vars:
            return f"{self.maps.short}:{expr.id}"
        return None

    def visit_With(self, node: ast.With) -> None:
        entered = 0
        cond_entered = 0
        for item in node.items:
            name = self.model.lock_at(item.context_expr, self.maps,
                                      self.cls)
            if name:
                for h in self.held:
                    self.info.with_edges.append((h, name, node.lineno))
                self.info.acquires.append((name, node.lineno))
                self.held.append(name)
                entered += 1
            else:
                cname = self._cond_name(item.context_expr)
                if cname:
                    self.cond_held.append(cname)
                    cond_entered += 1
        for child in node.body:
            self.visit(child)
        for _ in range(entered):
            self.held.pop()
        for _ in range(cond_entered):
            self.cond_held.pop()

    visit_AsyncWith = visit_With

    # -------------------------------------------- attribute use sites
    def _field_key(self, node: ast.Attribute) -> Optional[str]:
        """'Class.attr' / 'module:name' for a resolvable receiver, else
        None (never guessed)."""
        attr = node.attr
        if attr.startswith("__"):
            return None
        base = node.value
        if isinstance(base, ast.Name):
            if base.id == "self":
                return f"{self.cls}.{attr}" if self.cls else None
            # ClassName.attr class-var access (known class)
            if base.id in self.model._class_methods:
                return f"{base.id}.{attr}"
        rtype = self.model._receiver_type(base, self.maps, self.cls)
        if rtype:
            return f"{rtype}.{attr}"
        return None

    def _mark_sub_write(self, tgt: ast.AST) -> None:
        """`x[k] = v` / `del x[k]` / `x[k] += v` mutate the container
        `x` even though the receiver node carries Load ctx."""
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self._mark_sub_write(el)
        elif isinstance(tgt, ast.Starred):
            self._mark_sub_write(tgt.value)
        elif isinstance(tgt, ast.Subscript):
            v = tgt.value
            if isinstance(v, (ast.Attribute, ast.Name)):
                self._sub_writes.add(id(v))

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if id(node) not in self._method_attrs:
            key = self._field_key(node)
            if key is not None:
                if isinstance(node.ctx, (ast.Store, ast.Del)) or \
                        id(node) in self._sub_writes:
                    kind = "w"
                else:
                    kind = "r"
                self.info.attr_uses.append(
                    (kind, key, node.lineno,
                     tuple(self.held) + tuple(self.cond_held)))
        self.generic_visit(node)

    def visit_Name(self, node: ast.Name) -> None:
        name = node.id
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            if name in self.globals_decl:
                self.info.attr_uses.append(
                    ("w", f"{self.maps.short}:{name}", node.lineno,
                     tuple(self.held) + tuple(self.cond_held)))
        elif name in self.maps.module_globals and \
                name not in self.local_stores:
            kind = "w" if id(node) in self._sub_writes else "r"
            self.info.attr_uses.append(
                (kind, f"{self.maps.short}:{name}", node.lineno,
                 tuple(self.held) + tuple(self.cond_held)))

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._mark_sub_write(node.target)
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        for t in node.targets:
            self._mark_sub_write(t)
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._mark_sub_write(t)
        val = node.value
        if isinstance(val, ast.Call):
            fn = val.func
            if isinstance(fn, ast.Attribute) and \
                    isinstance(fn.value, ast.Name):
                if fn.value.id == "threading" and fn.attr == "Event":
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            self.local_events.add(t.id)
                if fn.value.id in self.maps.socket_aliases and \
                        fn.attr == "socket":
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            self.local_sockets.add(t.id)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        held = tuple(self.held)
        fn = node.func
        if isinstance(fn, ast.Attribute):
            # the method slot itself is not a field read; a mutating
            # container method IS a write to its receiver
            self._method_attrs.add(id(fn))
            if fn.attr in _MUTATORS and \
                    isinstance(fn.value, (ast.Attribute, ast.Name)) and \
                    self.model._receiver_type(
                        fn.value, self.maps, self.cls) is None:
                # typed receivers (Adder.add, Maxer.update...) are
                # domain calls, not raw container mutations — the
                # callee class's own fields get their own analysis
                self._sub_writes.add(id(fn.value))
        self._note_thread_target(node)
        handled = False
        # manual acquire of a discovered lock = acquisition event
        if isinstance(fn, ast.Attribute) and fn.attr == "acquire":
            name = self.model.lock_at(fn.value, self.maps, self.cls)
            if name:
                for h in self.held:
                    if h != name:
                        self.info.with_edges.append((h, name,
                                                     node.lineno))
                self.info.acquires.append((name, node.lineno))
                handled = True
        if not handled and id(node) not in self.awaited:
            why = self._blocking_reason(node)
            if why:
                self.info.blocking.append((node.lineno, why, held))
                if why == "time.sleep()" and self.loops > 0:
                    self.info.sleeps_in_loop.append(node.lineno)
                handled = True
            else:
                cb = self._callback_desc(node)
                if cb:
                    self.info.callbacks.append((node.lineno, cb, held))
        if not handled:
            desc = self._call_desc(node)
            if desc:
                self.info.calls.append(CallSite(desc, held, node.lineno))
        self.generic_visit(node)

    def _note_thread_target(self, node: ast.Call) -> None:
        fn = node.func
        is_thread = (isinstance(fn, ast.Attribute) and fn.attr == "Thread"
                     and isinstance(fn.value, ast.Name)
                     and fn.value.id == "threading")
        if not is_thread and isinstance(fn, ast.Name) and \
                fn.id == "Thread" and \
                self.maps.from_imports.get("Thread", ("",))[0] == \
                "threading":
            is_thread = True
        if not is_thread:
            return
        target = None
        tname = ""
        for kw in node.keywords:
            if kw.arg == "target":
                v = kw.value
                if isinstance(v, ast.Name):
                    target = ("bare", v.id)
                elif isinstance(v, ast.Attribute) and \
                        isinstance(v.value, ast.Name) and \
                        v.value.id == "self":
                    target = ("self", v.attr)
            elif kw.arg == "name" and isinstance(kw.value, ast.Constant) \
                    and isinstance(kw.value.value, str):
                tname = kw.value.value
        if target is not None:
            self.info.thread_targets.append((target, tname, node.lineno))

    # ------------------------------------------------------ classifiers
    def _blocking_reason(self, node: ast.Call) -> Optional[str]:
        fn = node.func
        maps = self.maps
        if isinstance(fn, ast.Name):
            if fn.id in maps.direct_sleep:
                return "time.sleep()"
            if fn.id in maps.direct_subprocess:
                return f"subprocess.{fn.id}()"
            return None
        if not isinstance(fn, ast.Attribute):
            return None
        base = fn.value
        bname = base.id if isinstance(base, ast.Name) else None
        battr = base.attr if isinstance(base, ast.Attribute) else None
        if bname in maps.time_aliases and fn.attr == "sleep":
            return "time.sleep()"
        if bname in maps.subprocess_aliases and \
                fn.attr in _SUBPROCESS_BLOCKING:
            return f"subprocess.{fn.attr}()"
        if bname in maps.socket_aliases and \
                fn.attr == "create_connection":
            return "socket.create_connection()"
        if fn.attr in ("connect", "accept", "recv", "recvfrom",
                       "sendall", "makefile"):
            if bname in self.local_sockets:
                return f"blocking socket.{fn.attr}()"
        if fn.attr == "wait":
            recv = _recv_name(base)
            # a receiver also used as `with X:` in this function is a
            # Condition (wait releases the lock) — not a blocking hazard
            if recv and recv in self.with_ctxs:
                return None
            if bname in self.local_events:
                return "threading.Event.wait()"
            is_event_attr = (battr is not None and isinstance(
                base, ast.Attribute) and isinstance(base.value, ast.Name)
                and base.value.id == "self" and self.cls
                and (self.cls, battr) in self.model._event_attrs)
            if is_event_attr:
                return "threading.Event.wait()"
            if recv and ("_ev" in recv or "event" in recv.lower()
                         or recv.endswith("_done")):
                return f"{recv}.wait()"
        return None

    def _callback_desc(self, node: ast.Call) -> Optional[str]:
        fn = node.func
        name = None
        if isinstance(fn, ast.Attribute):
            name = fn.attr
        elif isinstance(fn, ast.Name):
            name = fn.id
        elif isinstance(fn, ast.Subscript):
            v = fn.value
            vn = v.attr if isinstance(v, ast.Attribute) else (
                v.id if isinstance(v, ast.Name) else None)
            if vn and any(h in vn.lower()
                          for h in ("hook", "callback", "cbs", "_cb")):
                return f"stored callback {vn}[...]"
            return None
        if name is None:
            return None
        low = name.lower()
        if (low.startswith("on_") or "callback" in low
                or low.endswith("_cb") or low == "cb"
                or "hook" in low) and not low.startswith("on_event_"):
            kind = "stored callback" if isinstance(fn, ast.Attribute) \
                else "callback parameter"
            return f"{kind} {name}()"
        if isinstance(fn, ast.Attribute) and \
                name in ("write", "write_nowait", "sendall", "send"):
            # the socket-write clause applies ABOVE the wire machinery:
            # transport/ and protocol/ ARE the write path and serialize
            # fd writes under their own locks by design
            rel = self.maps.relpath
            if "/transport/" in rel or "/protocol/" in rel:
                return None
            recv = _recv_name(fn.value)
            if recv and any(s in recv.lower() for s in _SOCKETISH):
                return f"socket write {recv}.{name}()"
        return None

    def _call_desc(self, node: ast.Call) -> Optional[tuple]:
        fn = node.func
        if isinstance(fn, ast.Name):
            return ("bare", fn.id)
        if isinstance(fn, ast.Attribute):
            base = fn.value
            if isinstance(base, ast.Name):
                if base.id == "self":
                    return ("self", fn.attr)
                return ("attr", ("name", base.id), fn.attr)
            if isinstance(base, ast.Attribute) and \
                    isinstance(base.value, ast.Name) and \
                    base.value.id == "self":
                return ("attr", ("selfattr", base.attr), fn.attr)
            if isinstance(base, ast.Call) and \
                    isinstance(base.func, ast.Name):
                if base.func.id == "super":
                    return ("super", fn.attr)
                # factory-call receiver: global_dispatcher().pause_read()
                return ("attr", ("callret", base.func.id), fn.attr)
            return ("attr", ("expr",), fn.attr)
        return None


def _recv_name(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def get_lock_model(ctx: Context) -> LockModel:
    """The per-context singleton every lock rule shares."""
    model = getattr(ctx, "_lock_model", None)
    if model is None:
        model = LockModel(ctx)
        ctx._lock_model = model
    return model
