"""Thread-role model: which thread executes each function.

Seeds come from the lock model's resolved ``threading.Thread`` roots —
the dispatcher tick, ring-lane tick, timer thread, device poller,
fiber worker pool, shard supervisor, bvar sampler, flight-recorder
sampler, capture writer — plus every module's ``_postfork_reset``
handler (the fork child is single-threaded when they run). Each seed
is classified into a ROLE and the role propagates forward over the
resolved call graph: a function reachable from the dispatcher tick
runs (at least sometimes) on the dispatcher thread.

Two refinements keep the model honest rather than optimistic:

* a function reachable from several seeds carries several roles — the
  guarded-by rule treats fields written from multiple roles as shared
  state, ranked highest when unguarded;
* "external" is itself a role: any function reachable from an in-tree
  entry point that no seeded thread reaches (public API, helpers only
  tests call) may execute on an arbitrary caller thread. A function on
  both a seed path and an external path carries both roles, so
  `Socket.write()` called by user code *and* the dispatcher is never
  mistaken for thread-confined.

Single-thread roles (dispatcher, timer, poller, the samplers, the
supervisor, postfork) back the thread-confinement exemption: a field
written only from one single-thread role has a single writer by
construction and needs no lock. The fiber worker pool is N threads and
"external" is any number of caller threads — neither is single-thread.
"""

from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from brpc_tpu.analysis.core import Context
from brpc_tpu.analysis.lockmodel import LockModel, get_lock_model

#: Known thread entry points: (module suffix, qualname, role).
_SEED_ROLES: Tuple[Tuple[str, str, str], ...] = (
    ("transport.event_dispatcher", "EventDispatcher._run", "dispatcher"),
    ("transport.ring_lane", "RingDispatcher._run", "ring-dispatcher"),
    ("fiber.timer", "TimerThread._run", "timer"),
    ("fiber.device_poller", "DeviceEventPoller._run", "device-poller"),
    ("fiber.scheduler", "TaskControl._worker", "fiber"),
    ("rpc.shard_group", "ShardGroup._monitor_loop", "supervisor"),
    ("bvar.window", "Sampler._run", "bvar-sampler"),
    ("builtin.flight_recorder", "FlightRecorder._loop", "flight-sampler"),
    ("traffic.capture", "Recorder._record_writer_loop", "capture-writer"),
)

#: Roles backed by exactly one OS thread at a time. "fiber" (a pool)
#: and "external" (arbitrary caller threads) are deliberately absent,
#: as are ad-hoc "thread:<leaf>" roles for unrecognized future roots.
SINGLE_THREAD_ROLES: FrozenSet[str] = frozenset((
    "dispatcher", "ring-dispatcher", "timer", "device-poller",
    "supervisor", "bvar-sampler", "flight-sampler", "capture-writer",
    "postfork",
))

#: The synthetic role for code reachable only from unseeded entry
#: points — public API and helpers whose executing thread is whatever
#: the caller happens to be.
EXTERNAL = "external"

#: Functions that execute in a freshly forked CHILD process: a role
#: propagation boundary — the caller's thread does not exist on the
#: other side of os.fork(). They seed the (single-thread) postfork
#: role instead of inheriting the forking thread's.
_FORK_BOUNDARY = frozenset(("_child_main", "_postfork_reset",
                            "_postfork_child_reset"))


class ThreadModel:
    """Role assignment over the lock model's resolved call graph."""

    def __init__(self, model: LockModel):
        self.lock_model = model
        #: seed target fkey -> role name
        self.seeds: Dict[str, str] = {}
        #: fkey -> seeded roles that reach it (forward closure)
        self.roles: Dict[str, Set[str]] = {}
        #: fkeys reachable from role-less entry points (callable on
        #: arbitrary external threads)
        self.external: Set[str] = set()
        #: (fkey, role) -> call chain from the role's seed to fkey —
        #: the witness a finding prints so the reader can see WHICH
        #: thread reaches the access site and how
        self.chains: Dict[Tuple[str, str], Tuple[str, ...]] = {}
        self._build()

    # ------------------------------------------------------------ build
    def _classify_seed(self, fkey: str) -> str:
        mod, _, qual = fkey.partition("::")
        for suffix, leaf, role in _SEED_ROLES:
            if mod.endswith(suffix) and qual == leaf:
                return role
        # unrecognized future thread root: its own ad-hoc role, never
        # single-thread (no exemption granted on a guess)
        return "thread:" + qual.split(".")[-1].lstrip("_")

    @staticmethod
    def _forks(fkey: str) -> bool:
        return fkey.split("::")[-1].split(".")[-1] in _FORK_BOUNDARY

    def _reach(self, roots: List[str]) -> Set[str]:
        m = self.lock_model
        seen: Set[str] = set()
        pending = list(roots)
        while pending:
            cur = pending.pop()
            if cur in seen:
                continue
            seen.add(cur)
            info = m.funcs.get(cur)
            if info is None:
                continue
            for callee, _held, _line in info.resolved_calls:
                if callee not in seen and not self._forks(callee):
                    pending.append(callee)
        return seen

    def _reach_with_parents(self, root: str) -> Dict[str, Optional[str]]:
        """BFS forward closure keeping first-discovery parents, so a
        chain from the seed to any reached function can be rebuilt.
        Never crosses a fork boundary except out of the root itself."""
        m = self.lock_model
        parent: Dict[str, Optional[str]] = {root: None}
        queue = [root]
        while queue:
            cur = queue.pop(0)
            info = m.funcs.get(cur)
            if info is None:
                continue
            for callee, _held, _line in info.resolved_calls:
                if callee not in parent and not self._forks(callee):
                    parent[callee] = cur
                    queue.append(callee)
        return parent

    def _build(self) -> None:
        m = self.lock_model
        for _creator, fkey, _tname, _line in m.thread_roots:
            self.seeds[fkey] = self._classify_seed(fkey)
        for fkey in m.funcs:
            if self._forks(fkey):
                self.seeds.setdefault(fkey, "postfork")
        for root, role in sorted(self.seeds.items()):
            parent = self._reach_with_parents(root)
            for fkey in parent:
                self.roles.setdefault(fkey, set()).add(role)
                if (fkey, role) not in self.chains:
                    chain: List[str] = []
                    cur: Optional[str] = fkey
                    while cur is not None and len(chain) < 8:
                        chain.append(cur)
                        cur = parent.get(cur)
                    self.chains[(fkey, role)] = tuple(reversed(chain))
        # external closure: everything reachable from a non-seed entry
        # point with no in-tree caller may run on any caller thread
        callers: Set[str] = set()
        for info in m.funcs.values():
            for callee, _held, _line in info.resolved_calls:
                callers.add(callee)
        entries = [fkey for fkey in m.funcs
                   if fkey not in self.seeds and fkey not in callers]
        self.external = self._reach(entries)

    # ------------------------------------------------------------ query
    def roles_of(self, fkey: str) -> Set[str]:
        """Every role that may execute `fkey`, EXTERNAL included.
        Unknown functions get {EXTERNAL}: no claim means no exemption."""
        out = set(self.roles.get(fkey, ()))
        if fkey in self.external or not out:
            out.add(EXTERNAL)
        return out

    def seeded_roles_of(self, fkey: str) -> Set[str]:
        """Only the seeded thread roles reaching `fkey` (no EXTERNAL)."""
        return set(self.roles.get(fkey, ()))

    @staticmethod
    def is_single_thread(role: str) -> bool:
        return role in SINGLE_THREAD_ROLES

    def confined_to(self, fkeys: List[str]) -> Optional[str]:
        """The single single-thread role every function in `fkeys` is
        confined to, or None when they span threads."""
        combined: Set[str] = set()
        for fkey in fkeys:
            combined |= self.roles_of(fkey)
            if len(combined) > 1:
                return None
        if len(combined) == 1:
            role = next(iter(combined))
            if role in SINGLE_THREAD_ROLES:
                return role
        return None

    def chain_for(self, fkey: str, role: str) -> str:
        """Human-readable seed→site call chain for a (fkey, role)."""
        chain = self.chains.get((fkey, role))
        if not chain:
            return ""
        return " -> ".join(c.split("::")[-1] for c in chain)

    def role_table(self) -> List[Tuple[str, str]]:
        """(role, seed fkey) rows, stable order — docs + CLI surface."""
        return sorted(((role, fkey) for fkey, role in self.seeds.items()),
                      key=lambda r: (r[0], r[1]))


def get_thread_model(ctx: Context) -> ThreadModel:
    """The per-context singleton, riding the lock-model singleton."""
    tm = getattr(ctx, "_thread_model", None)
    if tm is None:
        tm = ThreadModel(get_lock_model(ctx))
        ctx._thread_model = tm
    return tm
