"""racelane: the lock model's dynamic complement — seeded schedule
perturbation and a runtime lock-order assert.

Static rules prove the acquisition GRAPH is clean; this module attacks
the schedules. ``install(seed=N)`` replaces ``threading.Lock`` /
``threading.RLock`` with instrumented twins that

  * inject a DETERMINISTIC yield/reorder point at lock acquisitions —
    whether acquisition #k at site S yields is a pure function of
    ``(seed, S, k)``, so a race found at seed N reproduces at seed N,
    every run (the chaos-lane discipline applied to the GIL scheduler:
    a yield right before an acquire is exactly the window a racing
    thread needs to get between a check and its act);
  * name themselves from their creation site (``module:attr`` parsed
    from the assignment source line — the same naming the static lock
    model uses), and, under ``BRPC_TPU_LOCK_DEBUG=1``, assert the
    DECLARED acquisition order from ``LOCK_ORDER`` at every acquire: a
    ranked lock taken while holding a higher-ranked one is recorded
    (and raised in strict mode) with both holders named.

The declared order below is the sanctioned registry published in
``docs/invariants.md`` — one line per lock, outermost first. Locks not
listed are unranked: they perturb but never trip the order assert.
Runtime naming matches registry rows by UNIQUE attribute suffix
(``_arb_lock``, ``lane_lock``, ...); rows whose attr is the generic
``_lock`` are ambiguous at runtime and covered by the static
lock-cycle rule only.

Wiring: ``brpc_tpu/__init__`` calls ``maybe_install_from_env()`` so
``BRPC_TPU_LOCK_DEBUG=1`` (with optional ``BRPC_TPU_LOCK_SEED``)
instruments every lock created after package import — tests spawn
their victim in a subprocess with the env set. The tier-2 lane
(``tests/test_racelane.py``) and the preflight smoke
(``python -m brpc_tpu.analysis.racelane --smoke``) replay the lint's
suspicious pairs as concrete interleavings on two threads.
"""

from __future__ import annotations

import linecache
import os
import re
import sys
import threading
import time
import zlib
from typing import Dict, List, Optional, Tuple

# ------------------------------------------------------ declared order
#
# The sanctioned lock acquisition order, OUTERMOST FIRST: a thread may
# only take a lock with a HIGHER rank index than everything it already
# holds. One line per lock, owner module named — docs/invariants.md
# publishes this table verbatim. Extend deliberately: append where the
# lock nests, never reorder existing entries without re-running the
# static lock-cycle rule and the racelane lane.
LOCK_ORDER: List[Tuple[str, str]] = [
    # (qualified lock name, owner module)
    ("Server._conns_lock",          "rpc/server.py"),
    ("ShardGroup._lock",            "rpc/shard_group.py"),
    ("ClusterChannel._sockets_lock", "rpc/cluster_channel.py"),
    ("Channel._socket_lock",        "rpc/channel.py"),
    ("Channel._pool_lock",          "rpc/channel.py"),
    ("Controller._arb_lock",        "rpc/controller.py"),
    ("Controller._lb_lock",         "rpc/controller.py"),
    ("LoadBalancer._lock",          "rpc/load_balancer.py"),
    ("CircuitBreaker._lock",        "rpc/circuit_breaker.py"),
    ("HealthChecker._lock",         "rpc/health_check.py"),
    ("backend_stats:_registry_lock", "rpc/backend_stats.py"),
    ("BackendStats._ring_lock",     "rpc/backend_stats.py"),
    ("BackendCell._lock",           "rpc/backend_stats.py"),
    ("ServingEngine._decode_lock",  "serving/engine.py"),
    ("ContinuousBatcher._lock",     "serving/batcher.py"),
    ("_StreamSender._lock",         "serving/service.py"),
    ("FlightRecorder._lock",        "builtin/flight_recorder.py"),
    ("Stream._grant_lock",          "rpc/stream.py"),
    ("ProgressiveAttachment._lock", "rpc/progressive.py"),
    ("Socket.lane_lock",            "transport/socket.py"),
    ("Socket._handoff_lock",        "transport/socket.py"),
    ("Socket.pending_lock",         "transport/socket.py"),
    ("Socket._failed_cb_lock",      "transport/socket.py"),
    ("Socket._lock",                "transport/socket.py"),
    ("EventDispatcher._lock",       "transport/event_dispatcher.py"),
    # ring-lane twin of the dispatcher lock: fd registry + tick-barrier
    # condvar (transport/ring_lane.py). Completion callbacks and the
    # write flush fire OUTSIDE it; inside it only native ring calls run,
    # so it never wraps another Python acquisition
    ("RingDispatcher._lock",        "transport/ring_lane.py"),
    ("socket_map:_glock",           "transport/socket_map.py"),
    ("IciConn._pump_lock",          "transport/ici.py"),
    ("IciConn._flush_lock",         "transport/ici.py"),
    ("IciConn._lock",               "transport/ici.py"),
    # leaf: device transfer cell — stamped by BatchTracker settle paths
    # that run under IciConn flush/pump holds; never wraps another
    # acquisition (transport/device_stats.py)
    ("DeviceCell._lock",            "transport/device_stats.py"),
    ("BlockPool._lock",             "butil/iobuf.py"),
    ("variable:_registry_lock",     "bvar/variable.py"),
    ("postfork:_lock",              "butil/postfork.py"),
    ("resource_census:_lock",       "butil/resource_census.py"),
    # leaf: drained inside Channel._retry_taken_call's _arb_lock hold
    # (the one sanctioned nesting); never wraps another acquisition
    ("RetryBudget._lock",           "rpc/retry_policy.py"),
    # leaf: the traffic recorder's queue lock — taken bare on the
    # dispatch completion path (on_complete) and by the writer's O(1)
    # queue swap; disk writes NEVER run under it (blocking-under-lock
    # mutation pin in tests/test_graftlint.py)
    ("Recorder._lock",              "traffic/capture.py"),
    # leaf: the trend-ring registry — settled on the bvar sampler's
    # tick thread AFTER every variable read (get_value / passive
    # callbacks run before the lock is taken); guards ring mutation
    # only, never wraps another acquisition (bvar/series.py)
    ("SeriesCollector._lock",       "bvar/series.py"),
    # leaf: the anomaly watchdog's key-state + incident ring — same
    # tick thread; span/flight-recorder annotation fires OUTSIDE it
    # (bvar/anomaly.py)
    ("AnomalyWatchdog._lock",       "bvar/anomaly.py"),
    # leaf: the DAGOR admission controller's window histogram — taken
    # bare on the dispatch admission path (admit_level) and by the
    # overload organs AFTER their own leaf locks released
    # (signal_overload runs once on_requested has returned False);
    # never wraps another acquisition (rpc/admission.py)
    ("AdmissionController._lock",   "rpc/admission.py"),
    # leaf: the channel-group budget registry — the shared bucket is
    # BUILT outside it (RetryBudget's constructor exposes a bvar, and
    # bvar registration must never nest under a registry lock); the
    # lock guards the dict insert/snapshot only (rpc/retry_policy.py)
    ("retry_policy:_group_lock",    "rpc/retry_policy.py"),
    # leaf: the incident manager's window state — arm/seal decisions
    # settle under it on the sampler tick, but recorder control, the
    # bundler thread spawn, and every disk write fire OUTSIDE it;
    # never wraps another acquisition (incident/manager.py)
    ("IncidentManager._lock",       "incident/manager.py"),
    # leaf: one per-method serving stat cell — the generation
    # tracker's waypoint stamps are plain attribute writes, so the
    # lock is taken ONCE per request lifetime (the settle latch +
    # counter/reservoir writes share the acquisition), always bare:
    # settles fire from _fire / the service shed path, outside every
    # batcher lock (serving/serving_stats.py)
    ("ServingCell._cell_lock",      "serving/serving_stats.py"),
    # leaf: the flight deck's bounded step ring — the batcher appends
    # its per-iteration record AFTER releasing its own lock and firing
    # callbacks; guards ring mutation only, never wraps another
    # acquisition (serving/serving_stats.py)
    ("ServingStats._ring_lock",     "serving/serving_stats.py"),
]

_RANK: Dict[str, int] = {name: i for i, (name, _) in enumerate(LOCK_ORDER)}

_ASSIGN_RE = re.compile(
    r"(?:self\.)?([A-Za-z_][A-Za-z0-9_]*)\s*=\s*"
    r"(?:threading\.)?(?:Lock|RLock)\s*\(")

# fallback for factory-indirected creation (Controller._LAZY via
# __getattr__): the creating line is `v = factory()`, but the frame
# ABOVE it is the attribute access (`with cntl._arb_lock:`) — a
# lock-ish attribute token there names the lock
_ATTR_RE = re.compile(
    r"[.\s(\[]([A-Za-z_][A-Za-z0-9_]*(?:lock|mutex)[A-Za-z0-9_]*)",
    re.IGNORECASE)


class LockOrderViolation(AssertionError):
    """A ranked lock was acquired while a higher-ranked one was held."""


class _State:
    """Module state for one install() session."""

    def __init__(self):
        self.installed = False
        self.seed = 0
        self.strict = False
        self.perturb = True
        self.yield_period = 7          # acquire #k yields when
        #                                hash(site, k, seed) % period == 0
        self.real_lock = None          # saved threading.Lock
        self.real_rlock = None         # saved threading.RLock
        self.acquires = 0              # global acquisition counter
        self.yields = 0
        self.violations: List[dict] = []
        self.lock_names: List[str] = []   # names seen at creation
        # per-THREAD ownership: .held = [(name, rank)] in acquisition
        # order, .counts = {id(lock): recursion depth}. Ownership must
        # be thread-local — an instance-level depth would make thread B
        # skip the order check whenever thread A happens to hold the
        # lock, which is exactly the moment the check matters — and
        # keyed by INSTANCE, not creation-site name: holding another
        # object's same-named lock is nesting to order-check, not
        # recursion to wave through (two Channels, two Sockets)
        self.tl = threading.local()

    def held(self) -> list:
        h = getattr(self.tl, "held", None)
        if h is None:
            h = self.tl.held = []
        return h

    def counts(self) -> dict:
        c = getattr(self.tl, "counts", None)
        if c is None:
            c = self.tl.counts = {}
        return c


_state = _State()


def _creation_site_name(depth: int = 2) -> str:
    """Name a lock from its creation source line — 'module:attr' like
    the static model. A direct assignment names at the creating frame;
    factory indirection (the Controller._LAZY `v = factory()` path)
    walks a few frames up to the attribute ACCESS that triggered the
    lazy creation (`with cntl._arb_lock:`) and names from its lock-ish
    token — so the real registry rows rank at runtime, not just the
    synthetic smoke locks."""
    try:
        for d in range(depth, depth + 4):
            try:
                f = sys._getframe(d)
            except ValueError:
                break
            fn, ln = f.f_code.co_filename, f.f_lineno
            line = linecache.getline(fn, ln)
            m = _ASSIGN_RE.search(line) or _ATTR_RE.search(line)
            if m:
                mod = os.path.basename(fn)
                if mod.endswith(".py"):
                    mod = mod[:-3]
                # self._x in a class: the runtime cannot see the class
                # name cheaply, so the registry matches by unique attr
                # suffix
                return f"{mod}:{m.group(1)}"
        return "<anon>:<anon>"
    except Exception:
        return "<anon>:<anon>"


def _rank_of(name: str) -> Optional[int]:
    attr = name.split(":")[-1]
    if name in _RANK:
        return _RANK[name]
    # unique attr suffix ('_arb_lock' names exactly one registry row)
    hits = [r for n, r in _RANK.items()
            if n.split(".")[-1] == attr or n.split(":")[-1] == attr]
    if len(hits) == 1:
        return hits[0]
    return None


def _registry_name(name: str) -> str:
    attr = name.split(":")[-1]
    hits = [n for n in _RANK
            if n.split(".")[-1] == attr or n.split(":")[-1] == attr]
    return hits[0] if len(hits) == 1 else name


def _perturb_point(site: str) -> None:
    """The deterministic yield: whether acquisition #k at this site
    yields is a pure function of (seed, site, k)."""
    st = _state
    st.acquires += 1
    if not st.perturb:
        return
    k = st.acquires
    # crc32, NOT builtin hash(): str hashing is PYTHONHASHSEED-salted
    # per process, and the whole point is that the yield schedule is a
    # pure function of (seed, site, k) ACROSS runs
    h = zlib.crc32(f"{st.seed}|{site}|{k}".encode())
    if h % st.yield_period == 0:
        st.yields += 1
        # a zero sleep is a real GIL release point: the OS scheduler
        # may run any other ready thread here
        time.sleep(0)


def _order_check(name: str, rank: Optional[int]) -> None:
    st = _state
    if rank is None:
        return
    held = st.held()
    for hname, hrank in held:
        if hrank is not None and hrank > rank:
            v = {"acquiring": _registry_name(name),
                 "acquiring_rank": rank,
                 "holding": _registry_name(hname),
                 "holding_rank": hrank,
                 "thread": threading.current_thread().name}
            st.violations.append(v)
            if st.strict:
                raise LockOrderViolation(
                    f"lock order inversion: acquiring "
                    f"{v['acquiring']} (rank {rank}) while holding "
                    f"{v['holding']} (rank {hrank}) — the declared "
                    "order in analysis/racelane.py:LOCK_ORDER says "
                    "the opposite nesting")
            break


class _DebugLockBase:
    """Shared instrumentation over a real lock primitive."""

    _factory = None        # set by install()

    def __init__(self):
        self._inner = self._factory()
        self.name = _creation_site_name(2)
        self.rank = _rank_of(self.name)
        _state.lock_names.append(self.name)

    # -- the threading.Lock protocol ---------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1):
        counts = _state.counts()
        mine = counts.get(id(self), 0)
        if blocking:
            _perturb_point(self.name)
            if mine == 0:
                # order is asserted on acquisition INTENT, before the
                # inner acquire: the deadlocked half of an AB/BA pair
                # never returns from acquire, so a post-acquire check
                # would record nothing exactly when it matters most.
                # (try-acquires are deadlock-safe by construction and
                # stay out of the assert.) In strict mode this raises
                # BEFORE anything is held — nothing leaks.
                _order_check(self.name, self.rank)
        got = self._inner.acquire(blocking, timeout)
        if got:
            counts[id(self)] = mine + 1
            if mine == 0:
                _state.held().append((self.name, self.rank))
        return got

    def release(self):
        self._inner.release()
        counts = _state.counts()
        mine = counts.get(id(self), 0)
        if mine:       # a cross-thread Lock release skips bookkeeping
            if mine == 1:
                counts.pop(id(self))     # no stale id-keyed entries
                held = _state.held()
                for i in range(len(held) - 1, -1, -1):
                    if held[i][0] == self.name:
                        del held[i]
                        break
            else:
                counts[id(self)] = mine - 1

    def locked(self):
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def _at_fork_reinit(self):
        self._inner = self._factory()


class DebugLock(_DebugLockBase):
    pass


class DebugRLock(_DebugLockBase):
    """RLock twin; also speaks the Condition protocol (_is_owned /
    _release_save / _acquire_restore) — the stdlib fallback probes
    ownership with a NON-reentrant acquire(False), which an RLock
    answers wrongly, so delegation here is load-bearing."""

    def _is_owned(self):
        return self._inner._is_owned()

    def _release_save(self):
        state = self._inner._release_save()
        counts = _state.counts()
        depth = counts.pop(id(self), 0)
        held = _state.held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] == self.name:
                del held[i]
                break
        return (state, depth)

    def _acquire_restore(self, saved):
        state, depth = saved
        self._inner._acquire_restore(state)
        _state.counts()[id(self)] = depth
        _state.held().append((self.name, self.rank))


def install(seed: Optional[int] = None, strict: bool = False,
            perturb: bool = True, yield_period: int = 7) -> None:
    """Instrument every lock created from now on. Idempotent."""
    st = _state
    if not st.installed:
        st.real_lock = threading.Lock
        st.real_rlock = threading.RLock
        DebugLock._factory = staticmethod(st.real_lock)
        DebugRLock._factory = staticmethod(st.real_rlock)
        threading.Lock = DebugLock
        threading.RLock = DebugRLock
        st.installed = True
    st.seed = 0 if seed is None else int(seed)
    st.strict = bool(strict)
    st.perturb = bool(perturb)
    st.yield_period = max(2, int(yield_period))


def uninstall() -> None:
    st = _state
    if st.installed:
        threading.Lock = st.real_lock
        threading.RLock = st.real_rlock
        st.installed = False


def installed() -> bool:
    return _state.installed


def violations() -> List[dict]:
    return list(_state.violations)


def clear_violations() -> None:
    _state.violations.clear()


def stats() -> dict:
    return {"installed": _state.installed, "seed": _state.seed,
            "acquires": _state.acquires, "yields": _state.yields,
            "locks_created": len(_state.lock_names),
            "violations": len(_state.violations)}


def maybe_install_from_env() -> bool:
    """The brpc_tpu/__init__ hook: BRPC_TPU_LOCK_DEBUG=1 instruments
    (order-asserting, perturbing with BRPC_TPU_LOCK_SEED, strict with
    BRPC_TPU_LOCK_DEBUG=strict)."""
    mode = os.environ.get("BRPC_TPU_LOCK_DEBUG", "")
    if mode not in ("1", "strict"):
        return False
    seed = 0
    try:
        seed = int(os.environ.get("BRPC_TPU_LOCK_SEED", "0"))
    except ValueError:
        pass
    install(seed=seed, strict=(mode == "strict"))
    return True


# ----------------------------------------------------------- replays

def replay_pair(setup, thread_a, thread_b, seed: int,
                timeout_s: float = 5.0) -> dict:
    """Replay a suspicious lock pair as a concrete interleaving: run
    ``thread_a``/``thread_b`` (callables taking the object built by
    ``setup()``) on two threads under seeded perturbation and report
    violations + completion (a hang within the timeout = potential
    deadlock, reported, threads abandoned as daemons)."""
    clear_violations()
    # apply the REQUESTED seed (replaying a race found at seed N must
    # actually run seed N, not whatever install() last set) and reset
    # the acquisition counter the yield schedule is keyed on, so the
    # same replay sees the same k sequence, run after run, process
    # after process
    _state.seed = int(seed)
    _state.acquires = 0
    obj = setup()
    done = [False, False]

    def run(fn, i):
        try:
            fn(obj)
        finally:
            done[i] = True

    ta = threading.Thread(target=run, args=(thread_a, 0), daemon=True)
    tb = threading.Thread(target=run, args=(thread_b, 1), daemon=True)
    ta.start()
    tb.start()
    ta.join(timeout_s)
    tb.join(timeout_s)
    return {"seed": seed, "completed": all(done),
            "violations": violations(),
            "stats": stats()}


# ------------------------------------------------- field-race replay
#
# The guarded-by rule's dynamic complement: a flagged site is a source
# LINE that mutates a field with the inferred guard not held. Replaying
# it means running the two implicated code paths on two threads while a
# per-thread trace hook injects a seeded GIL yield every time a racer
# is ABOUT to execute a flagged line — exactly the window a racing peer
# needs between the site's check and its act. A finding whose replay
# breaks the caller-supplied invariant ships as CONFIRMED with this
# reproducer (seed + sites); the rest stay ranked PLAUSIBLE.

def _parse_site(site) -> Tuple[str, int]:
    """'pkg/mod.py:123' or ('mod.py', 123) -> ('mod.py', 123)."""
    if isinstance(site, str):
        path, _, ln = site.rpartition(":")
        return os.path.basename(path), int(ln)
    path, ln = site
    return os.path.basename(str(path)), int(ln)


def _make_tracer(files, lines, quals, seed, tix):
    """One tracer per racer thread. The call-event filter keeps the
    line hook out of every frame not under watch, so the replay's
    overhead stays on the implicated functions only. Yield decisions
    are a pure function of (seed, thread index, site, hit #) — the
    schedule replays exactly, run after run."""
    k = [0]

    leaves = {q.split(".")[-1] for q in quals}

    def match_qual(code) -> bool:
        # suffix match on a dot boundary: functions built inside a
        # factory carry '<locals>.' prefixes in co_qualname. Before
        # 3.11 code objects have no co_qualname — fall back to the
        # bare name (a looser match that only ever ADDS yield points)
        qual = getattr(code, "co_qualname", None)
        if qual is None:
            return code.co_name in leaves
        return any(qual == q or qual.endswith("." + q) for q in quals)

    def line_hook(frame, event, arg):
        if event != "line":
            return line_hook
        code = frame.f_code
        bn = os.path.basename(code.co_filename)
        if (bn, frame.f_lineno) in lines or match_qual(code):
            k[0] += 1
            h = zlib.crc32(f"{seed}|{tix}|{bn}:{frame.f_lineno}|"
                           f"{k[0]}".encode())
            if h % 2 == 0:
                _state.yields += 1
                # a POSITIVE sleep, unlike the lock twins' sleep(0):
                # a zero sleep often re-acquires the GIL before the
                # peer's condvar wakes, silently serializing the
                # replay — 20us forces a real handoff into the window
                time.sleep(0.00002)
        return line_hook

    def call_hook(frame, event, arg):
        code = frame.f_code
        if os.path.basename(code.co_filename) in files or \
                match_qual(code):
            return line_hook
        return None

    return call_hook


def replay_field_race(setup, racer_a, racer_b, sites, seed: int = 0,
                      check=None, timeout_s: float = 10.0) -> dict:
    """Replay a guarded-by finding as a concrete interleaving.

    ``setup()`` builds the victim object; ``racer_a``/``racer_b`` are
    the two implicated code paths (callables taking the object);
    ``sites`` mixes flagged source lines (``'path.py:123'`` strings or
    ``(file, line)`` pairs) with function qualnames (every line of the
    function is a yield point — drift-proof against edits). After both
    racers finish, ``check(obj)`` validates the field's invariant; its
    message is the reproducer's evidence. Returns ``{seed, completed,
    site_yields, ok, evidence}``."""
    lines = set()
    quals = set()
    for s in sites:
        if isinstance(s, str) and ":" not in s:
            quals.add(s)
        else:
            lines.add(_parse_site(s))
    files = {f for f, _ in lines}
    y0 = _state.yields
    obj = setup()
    done = [False, False]
    errs: List[str] = []
    # both racers align here before racing: without it the first
    # thread routinely finishes before the second's OS thread even
    # starts, and a serialized run can confirm nothing
    barrier = threading.Barrier(2)

    def run(fn, i):
        barrier.wait(timeout_s)
        sys.settrace(_make_tracer(files, lines, quals, seed, i))
        try:
            fn(obj)
        except Exception as e:   # noqa: BLE001 - the report carries it
            errs.append(f"racer_{'ab'[i]}: {e!r}")
        finally:
            sys.settrace(None)
            done[i] = True

    ta = threading.Thread(target=run, args=(racer_a, 0), daemon=True)
    tb = threading.Thread(target=run, args=(racer_b, 1), daemon=True)
    ta.start()
    tb.start()
    ta.join(timeout_s)
    tb.join(timeout_s)
    completed = all(done)
    evidence = list(errs)
    ok = completed and not errs
    if ok and check is not None:
        try:
            verdict = check(obj)
            if verdict not in (None, True):
                ok = False
                evidence.append(str(verdict))
        except AssertionError as e:
            ok = False
            evidence.append(str(e) or "invariant check failed")
    if not completed:
        evidence.append(f"racers hung past {timeout_s}s "
                        "(potential deadlock; daemons abandoned)")
    return {"seed": seed, "completed": completed,
            "site_yields": _state.yields - y0,
            "ok": ok, "evidence": evidence}


# The suspicious-pair list the preflight smoke replays: each entry is a
# named builder returning (setup, racer_a, racer_b, sites, check,
# expect_race). `expect_race=True` rows are positive controls — the
# replay MUST break their invariant (the harness detects real races);
# `False` rows are fixed findings — the replay must leave the
# invariant intact (the regression stays dead at this seed).

def _pair_unguarded_counter():
    """Positive control: the textbook lost update. The read-modify-
    write is split across two lines so the line hook can yield inside
    the window; 2x200 increments with no lock must lose some."""
    class _Cell:
        def __init__(self):
            self.x = 0

        def bump(self):
            t = self.x
            self.x = t + 1

    def racer(o):
        for _ in range(200):
            o.bump()

    def check(o):
        assert o.x == 400, f"lost update: {o.x}/400 after 2x200 bumps"

    return _Cell, racer, racer, ["_Cell.bump"], check, True


def _pair_guarded_counter():
    """The same counter with its guard held: zero lost updates under
    the identical yield schedule — the twin that proves detection is
    the race, not the harness."""
    class _Cell:
        def __init__(self):
            self._lock = threading.Lock()
            self.x = 0

        def bump(self):
            with self._lock:
                t = self.x
                self.x = t + 1

    def racer(o):
        for _ in range(200):
            o.bump()

    def check(o):
        assert o.x == 400, f"guarded counter lost updates: {o.x}"

    return _Cell, racer, racer, ["_Cell.bump"], check, False


def _pair_taskcontrol_stop_vs_start():
    """The fixed ISSUE-16 finding: TaskControl.stop_and_join used to
    clear _threads and drop _started/_stop with no lock while start()
    published the pool under _start_lock — a start() landing in the
    teardown window left a pool that CLAIMS started with every worker
    dead (spawned fibers never run). Yields at every line of both
    verbs drive the interleaving; the invariant is 'started implies a
    live worker'."""
    from brpc_tpu.fiber.scheduler import TaskControl

    def setup():
        return TaskControl(concurrency=2, name="racelane_tc")

    def starter(tc):
        for _ in range(6):
            tc.start()
            time.sleep(0)

    def stopper(tc):
        for _ in range(6):
            tc.stop_and_join(timeout=2.0)

    def check(tc):
        try:
            with tc._start_lock:
                started = tc._started
                alive = [t for t in tc._threads if t.is_alive()]
            assert not started or alive, (
                "pool claims started with no live worker: start() "
                "landed inside stop_and_join's teardown window")
        finally:
            tc.stop_and_join(timeout=2.0)

    return (setup, starter, stopper,
            ["TaskControl.start", "TaskControl.stop_and_join"],
            check, False)


SUSPICIOUS_PAIRS = [
    ("unguarded-counter", _pair_unguarded_counter),
    ("guarded-counter", _pair_guarded_counter),
    ("taskcontrol-stop-vs-start", _pair_taskcontrol_stop_vs_start),
]


def replay_suspicious_pairs(seed: int = 0) -> dict:
    """Run every registered pair; ok = every positive control raced
    and every fixed finding held its invariant."""
    out: dict = {"pairs": {}, "ok": True}
    for name, build in SUSPICIOUS_PAIRS:
        setup, ra, rb, sites, check, expect_race = build()
        r = replay_field_race(setup, ra, rb, sites, seed=seed,
                              check=check)
        raced = not r["ok"]
        good = r["completed"] and (raced == expect_race)
        out["pairs"][name] = {"expect_race": expect_race,
                              "raced": raced, **r}
        out["ok"] = out["ok"] and good
    return out


# ------------------------------------------------------------- smoke

def _smoke() -> dict:
    """The preflight lane: (1) a seeded synthetic AB/BA inversion must
    be DETECTED deterministically (same seed, same verdict, run twice);
    (2) the real serving batcher under perturbation + order assert runs
    a submit/step/cancel storm with zero violations."""
    report: dict = {"ok": False}
    try:
        seed = int(os.environ.get("BRPC_TPU_LOCK_SEED", "0") or "0")
    except ValueError:
        seed = 0
    if not _state.installed:
        install(seed=seed)
    else:
        # the package import hook installed with the seed the env had
        # THEN — a --seed passed to the CLI must still win
        _state.seed = seed

    # -- (1) synthetic inversion: two registry-ranked locks taken in
    # the wrong order on thread B while thread A uses the sanctioned
    # order. The order assert must flag B's inversion both runs.
    def build_pair():
        class _Arb:                       # mimic the registry rows
            pass
        o = _Arb()
        o._arb_lock = threading.RLock()   # rank: Controller._arb_lock
        o._lb_lock = threading.Lock()     # rank: Controller._lb_lock
        return o

    def good_path(o):
        for _ in range(20):
            with o._arb_lock:
                with o._lb_lock:          # sanctioned: arb then lb
                    pass

    def bad_path(o):
        for _ in range(20):
            with o._lb_lock:
                with o._arb_lock:         # inversion: lb then arb
                    pass

    runs = []
    for _ in range(2):
        r = replay_pair(build_pair, good_path, bad_path, _state.seed,
                        timeout_s=2.0)
        runs.append({"completed": r["completed"],
                     "deadlocked": not r["completed"],
                     "violations": len(r["violations"]),
                     "first": (r["violations"][0]
                               if r["violations"] else None)})
    report["seeded_inversion"] = runs
    # the assert fires on acquisition INTENT: the inversion is recorded
    # even when the pair genuinely deadlocks (the perturbation makes
    # that likely — which is the point; the replay abandons the
    # daemonized pair and reports the hang as evidence)
    detected = all(r["violations"] > 0 for r in runs)
    deterministic = (runs[0]["first"] is not None
                     and runs[1]["first"] is not None
                     and runs[0]["first"]["acquiring"]
                     == runs[1]["first"]["acquiring"]
                     and runs[0]["first"]["holding"]
                     == runs[1]["first"]["holding"])
    report["inversion_detected"] = detected
    report["inversion_deterministic"] = deterministic

    # -- (2) real code under perturbation: batcher submit/step/cancel
    clear_violations()
    from brpc_tpu.serving.batcher import ContinuousBatcher, GenRequest
    b = ContinuousBatcher(max_batch=2, max_waiting=8)
    errs: List[str] = []

    def submitter():
        for i in range(24):
            try:
                b.submit(GenRequest([1, 2, 3], 4))
            except Exception as e:   # noqa: BLE001 - report, don't die
                errs.append(f"submit: {e!r}")

    def stepper():
        for _ in range(60):
            try:
                b.step()
            except Exception as e:   # noqa: BLE001
                errs.append(f"step: {e!r}")

    ts = [threading.Thread(target=submitter, daemon=True),
          threading.Thread(target=stepper, daemon=True)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(30.0)
    b.stop()
    real_viol = violations()
    report["real_code"] = {"errors": errs[:5],
                           "violations": real_viol[:5],
                           "stats": stats()}
    report["real_code_clean"] = not errs and not real_viol

    # -- (3) the guarded-by suspicious-pair list: positive controls
    # must race, fixed findings must hold their invariant
    report["field_races"] = replay_suspicious_pairs(_state.seed)
    report["ok"] = bool(detected and deterministic
                        and report["real_code_clean"]
                        and report["field_races"]["ok"])
    return report


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    import json
    p = argparse.ArgumentParser(
        prog="racelane",
        description="seeded lock-schedule perturbation + order assert")
    p.add_argument("--smoke", action="store_true",
                   help="run the seeded-interleaving smoke (JSON out)")
    p.add_argument("--seed", type=int, default=None)
    args = p.parse_args(argv)
    if args.seed is not None:
        os.environ["BRPC_TPU_LOCK_SEED"] = str(args.seed)
    if not args.smoke:
        p.print_help()
        return 2
    report = _smoke()
    print(json.dumps(report, indent=2, default=str))
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    # delegate to the canonical module object: under -m the package
    # __init__ may already have imported (and installed from) the
    # brpc_tpu.analysis.racelane copy — running the smoke on a second
    # __main__ copy would split _state across two modules
    from brpc_tpu.analysis import racelane as _canonical

    sys.exit(_canonical.main())
