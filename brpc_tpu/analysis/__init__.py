"""graftlint: framework-invariant static analysis for brpc_tpu.

The framework's correctness rests on a handful of cross-cutting
invariants no unit test can guard globally — fibers must not block
their carrier pthread, IOBufs handed to the write path must not be
mutated afterwards, every native fast lane must judge-or-defer to the
classic lane, lock acquisition order must be acyclic, and every
registered protocol must be complete. ``graftlint`` walks the package
ASTs (plus the native C++ sources) and enforces each invariant as a
pluggable rule; see docs/invariants.md for the catalogue and the
waiver syntax (``# graftlint: disable=<rule> -- reason``).

Run it:
    python -m brpc_tpu.analysis brpc_tpu/
    python tools/graftlint.py brpc_tpu/ --json
"""

from brpc_tpu.analysis.core import (  # noqa: F401
    Analyzer, Finding, Rule, SourceFile,
)


def run(paths, rules=None):
    """Analyze ``paths`` and return (active, waived) finding lists."""
    a = Analyzer(rules=rules)
    return a.run(paths)
