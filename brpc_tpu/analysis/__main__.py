"""graftlint CLI: ``python -m brpc_tpu.analysis [paths...]``.

Exit code = the UNWAIVED finding count (capped at 100) so CI can gate
on zero and scripts can read severity without parsing; usage/internal
errors exit 120. Machine consumers pick ``--format=json`` or
``--format=sarif`` (SARIF 2.1.0 — editors and code-scanning UIs);
``--changed [BASE]`` lints only files touched vs a git base ref
(default HEAD) while still analyzing the whole tree for cross-module
context; ``--show-waivers`` audits every waiver in force (file:line,
rules, reason, and whether it suppressed anything this run).
``--baseline FILE`` suppresses the findings recorded by a previous
``--write-baseline FILE`` (keyed path+rule+message, line-drift-proof)
so CI fails only on NEW findings; ``--field-guards`` prints the
guarded-by rule's inferred field->guard registry — the table
docs/invariants.md publishes.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from typing import List, Optional, Set

from brpc_tpu.analysis.core import Analyzer, iter_source_files

EXIT_CAP = 100         # finding-count exit codes stay below...
EXIT_USAGE = 120       # ...the usage/internal-error code


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="graftlint",
        description="framework-invariant static analysis for brpc_tpu")
    p.add_argument("paths", nargs="*", default=["brpc_tpu"],
                   help="files or directories to analyze "
                        "(default: brpc_tpu)")
    p.add_argument("--rules", metavar="R1,R2",
                   help="run only these rules (comma-separated names)")
    p.add_argument("--list-rules", action="store_true",
                   help="list available rules and exit")
    p.add_argument("--format", choices=("text", "json", "sarif"),
                   default="text", dest="fmt",
                   help="output format (default text)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="alias for --format=json")
    p.add_argument("--changed", nargs="?", const="HEAD", default=None,
                   metavar="BASE",
                   help="report only findings in files changed vs the "
                        "git base ref (default HEAD); the whole tree "
                        "is still analyzed for cross-module context")
    p.add_argument("--show-waived", action="store_true",
                   help="also print waived findings (with reasons)")
    p.add_argument("--show-waivers", action="store_true",
                   help="list every waiver in force (file:line, rules, "
                        "reason, used/unused this run) and exit 0")
    p.add_argument("--field-guards", action="store_true",
                   help="print the inferred field->guard registry "
                        "(the docs/invariants.md table) and exit 0")
    p.add_argument("--baseline", metavar="FILE", default=None,
                   help="suppress findings recorded in FILE (written "
                        "by --write-baseline): CI diffs against the "
                        "committed baseline instead of failing on "
                        "known rows")
    p.add_argument("--write-baseline", metavar="FILE", default=None,
                   help="write the current active findings to FILE "
                        "and exit 0")
    return p


def _baseline_key(f) -> tuple:
    """Baseline identity deliberately drops the line number: unrelated
    edits shift lines, and a baseline that churns on every edit gets
    regenerated blindly instead of reviewed."""
    return (f.path, f.rule, f.message)


def load_baseline(path: str) -> Optional[Set[tuple]]:
    try:
        with open(path, encoding="utf-8") as fh:
            data = json.load(fh)
    except (OSError, ValueError):
        return None
    keys: Set[tuple] = set()
    for row in data.get("findings", ()):
        keys.add((row.get("path", ""), row.get("rule", ""),
                  row.get("message", "")))
    return keys


def changed_files(base: str, repo_root: str) -> Optional[Set[str]]:
    """Absolute paths of .py/.cc files changed vs base (tracked diff +
    untracked); None when git is unavailable."""
    out: Set[str] = set()
    try:
        diff = subprocess.run(
            ["git", "diff", "--name-only", base, "--"],
            cwd=repo_root, capture_output=True, text=True, timeout=60)
        if diff.returncode != 0:
            return None
        untracked = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard"],
            cwd=repo_root, capture_output=True, text=True, timeout=60)
    except (OSError, subprocess.SubprocessError):
        return None
    names = diff.stdout.splitlines()
    if untracked.returncode == 0:
        names += untracked.stdout.splitlines()
    for n in names:
        if n.endswith((".py", ".cc")):
            out.add(os.path.abspath(os.path.join(repo_root, n)))
    return out


def _git_root() -> str:
    try:
        proc = subprocess.run(
            ["git", "rev-parse", "--show-toplevel"],
            capture_output=True, text=True, timeout=30)
        if proc.returncode == 0 and proc.stdout.strip():
            return proc.stdout.strip()
    except (OSError, subprocess.SubprocessError):
        pass
    return os.getcwd()


def to_sarif(active, waived, rules) -> dict:
    """Minimal valid SARIF 2.1.0: one run, one result per ACTIVE
    finding (waived findings ride along as suppressed results)."""
    rule_meta = [{"id": r.name,
                  "shortDescription": {"text": r.description or r.name}}
                 for r in rules]

    def result(f, suppressed: bool) -> dict:
        out = {
            "ruleId": f.rule,
            "level": "error",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path},
                    "region": {"startLine": max(1, f.line)},
                },
            }],
        }
        if suppressed:
            out["suppressions"] = [{
                "kind": "inSource",
                "justification": f.reason or "",
            }]
        return out

    return {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "graftlint",
                "informationUri": "docs/invariants.md",
                "rules": rule_meta,
            }},
            "results": ([result(f, False) for f in active]
                        + [result(f, True) for f in waived]),
        }],
    }


def collect_waivers(paths: List[str], waived_findings) -> List[dict]:
    """Every waiver comment in force across the scanned files: location,
    rules, reason, and whether it suppressed a finding this run."""
    used_lines = {(f.path, f.line) for f in waived_findings}

    def filewide_used(sf, rule: str) -> bool:
        # a file-wide waiver only did the suppressing when no LINE
        # waiver covered the finding (waiver_reason matches the line
        # slot first) — keyed any looser, a stale disable-file hides
        # behind its line-level siblings and escapes the UNUSED audit
        for f in waived_findings:
            if f.path != sf.relpath:
                continue
            if rule != "all" and f.rule != rule:
                continue
            dis = sf.waivers.get(f.line, ())
            if f.rule not in dis and "all" not in dis:
                return True
        return False

    merged: dict = {}
    for sf in iter_source_files(paths):
        if "/analysis/" in sf.relpath:
            continue       # the linter's own docs show waiver EXAMPLES
        for slot, names in sorted(sf.waivers.items()):
            for name in sorted(names):
                reason = sf.reasons.get((slot, name), "")
                # a comment-above waiver occupies TWO slots (the
                # comment line and the covered code line): merge them
                # into one audit row, marked used if either fired
                key = (sf.relpath, name, reason)
                used = ((sf.relpath, slot) in used_lines if slot
                        else filewide_used(sf, name))
                row = merged.get(key)
                if row is None:
                    merged[key] = {
                        "path": sf.relpath,
                        "line": slot or 0,      # 0 = file-wide
                        "rule": name,
                        "reason": reason,
                        "file_wide": slot == 0,
                        "used": used,
                    }
                else:
                    row["used"] = row["used"] or used
                    if slot and (row["line"] == 0 or slot < row["line"]):
                        row["line"] = slot
    return sorted(merged.values(),
                  key=lambda w: (w["path"], w["line"], w["rule"]))


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    fmt = "json" if args.as_json else args.fmt
    from brpc_tpu.analysis.rules import default_rules
    rules = default_rules()
    if args.list_rules:
        for r in rules:
            print(f"{r.name:24} {r.description}")
        return 0
    if args.rules:
        wanted = {n.strip() for n in args.rules.split(",") if n.strip()}
        unknown = wanted - {r.name for r in rules}
        if unknown:
            print(f"graftlint: unknown rules: {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return EXIT_USAGE
        rules = [r for r in rules if r.name in wanted]
    paths = args.paths or ["brpc_tpu"]

    if args.field_guards:
        from brpc_tpu.analysis.core import Context
        from brpc_tpu.analysis.rules.guarded_by import (
            field_guard_table, render_field_guards,
        )
        ctx = Context(iter_source_files(paths))
        if fmt == "json":
            print(json.dumps({"field_guards": field_guard_table(ctx)}))
        else:
            print(render_field_guards(ctx))
        return 0

    analyzer = Analyzer(rules=rules)
    active, waived = analyzer.run(paths)

    if args.write_baseline:
        with open(args.write_baseline, "w", encoding="utf-8") as fh:
            json.dump({"findings": [f.to_dict() for f in active]},
                      fh, indent=1, sort_keys=True)
            fh.write("\n")
        print(f"graftlint: baseline with {len(active)} finding(s) "
              f"written to {args.write_baseline}", file=sys.stderr)
        return 0
    if args.baseline:
        known = load_baseline(args.baseline)
        if known is None:
            print(f"graftlint: cannot read baseline {args.baseline}",
                  file=sys.stderr)
            return EXIT_USAGE
        active = [f for f in active if _baseline_key(f) not in known]

    if args.show_waivers:
        waivers = collect_waivers(paths, waived)
        if fmt == "json":
            print(json.dumps({"waivers": waivers}))
        else:
            for w in waivers:
                where = (f"{w['path']}:{'file-wide' if w['file_wide'] else w['line']}")
                mark = "" if w["used"] else " (UNUSED — stale?)"
                print(f"{where}: disable={w['rule']}{mark}"
                      f" -- {w['reason'] or '<no reason>'}")
            print(f"graftlint: {len(waivers)} waiver(s) in force",
                  file=sys.stderr)
        return 0

    if args.changed is not None:
        repo_root = _git_root()
        changed = changed_files(args.changed, repo_root)
        if changed is None:
            print("graftlint: --changed needs a git checkout",
                  file=sys.stderr)
            return EXIT_USAGE
        active = [f for f in active
                  if os.path.abspath(os.path.join(repo_root, f.path))
                  in changed]
        waived = [f for f in waived
                  if os.path.abspath(os.path.join(repo_root, f.path))
                  in changed]

    exit_code = min(len(active), EXIT_CAP)
    if fmt == "json":
        print(json.dumps({
            "active": [f.to_dict() for f in active],
            "waived": [f.to_dict() for f in waived],
            "rules": [r.name for r in rules],
        }, indent=None))
        return exit_code
    if fmt == "sarif":
        print(json.dumps(to_sarif(active, waived, rules)))
        return exit_code
    for f in active:
        print(f.format())
    if args.show_waived:
        for f in waived:
            print(f.format() + (f" [reason: {f.reason}]"
                                if f.reason else ""))
    n_w = len(waived)
    if active:
        print(f"graftlint: {len(active)} finding(s)"
              f" ({n_w} waived)", file=sys.stderr)
    else:
        print(f"graftlint: clean ({n_w} waived)", file=sys.stderr)
    return exit_code


if __name__ == "__main__":
    sys.exit(main())
