"""graftlint CLI: ``python -m brpc_tpu.analysis [paths...]``.

Exit codes: 0 clean (or every finding waived with a reason), 1 active
findings, 2 usage/internal error.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from brpc_tpu.analysis.core import Analyzer


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="graftlint",
        description="framework-invariant static analysis for brpc_tpu")
    p.add_argument("paths", nargs="*", default=["brpc_tpu"],
                   help="files or directories to analyze "
                        "(default: brpc_tpu)")
    p.add_argument("--rules", metavar="R1,R2",
                   help="run only these rules (comma-separated names)")
    p.add_argument("--list-rules", action="store_true",
                   help="list available rules and exit")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit findings as one JSON object on stdout")
    p.add_argument("--show-waived", action="store_true",
                   help="also print waived findings (with reasons)")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    from brpc_tpu.analysis.rules import default_rules
    rules = default_rules()
    if args.list_rules:
        for r in rules:
            print(f"{r.name:18} {r.description}")
        return 0
    if args.rules:
        wanted = {n.strip() for n in args.rules.split(",") if n.strip()}
        unknown = wanted - {r.name for r in rules}
        if unknown:
            print(f"graftlint: unknown rules: {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2
        rules = [r for r in rules if r.name in wanted]
    analyzer = Analyzer(rules=rules)
    active, waived = analyzer.run(args.paths or ["brpc_tpu"])
    if args.as_json:
        print(json.dumps({
            "active": [f.to_dict() for f in active],
            "waived": [f.to_dict() for f in waived],
            "rules": [r.name for r in rules],
        }, indent=None))
        return 1 if active else 0
    for f in active:
        print(f.format())
    if args.show_waived:
        for f in waived:
            print(f.format() + (f" [reason: {f.reason}]"
                                if f.reason else ""))
    n_w = len(waived)
    if active:
        print(f"graftlint: {len(active)} finding(s)"
              f" ({n_w} waived)", file=sys.stderr)
        return 1
    print(f"graftlint: clean ({n_w} waived)", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
