"""lock-order: the static lock graph over ``with <lock>:`` nesting
must be acyclic.

Two code paths that take the same pair of locks in opposite orders
deadlock under concurrency; with fibers multiplexed onto carrier
pthreads the window is wider than it looks (a parked fiber holds its
Python locks across suspension). The rule builds a conservative
static graph:

  * a ``with A:`` containing a nested ``with B:`` adds edge A -> B
    (also through ``with A, B:`` multi-item forms);
  * a call made while holding A to a same-module function/method that
    itself takes B adds A -> B (one-hop call closure, fixpointed);
  * lock identity is the qualified attribute name — ``Class._x_lock``
    for ``self._x_lock``, ``module:_lock`` for module globals — so
    distinct instances of the same class attribute share a node
    (conservative: instance-level cycles are reported even when
    runtime instances differ; waive with a reason where that split is
    load-bearing).

Only names that look like locks (``*lock*``) participate; ``with``
over files/portals/contexts stays out of the graph. Reported once per
cycle, at the first edge's location.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from brpc_tpu.analysis.core import Context, Finding, Rule, SourceFile



def _lock_name(node: ast.AST, module: str,
               cls: Optional[str]) -> Optional[str]:
    """Qualified lock node name, or None when the expr isn't lock-like."""
    if isinstance(node, ast.Attribute) and "lock" in node.attr.lower():
        base = node.value
        if isinstance(base, ast.Name) and base.id == "self" and cls:
            return f"{cls}.{node.attr}"
        return f"{module}:{node.attr}"
    if isinstance(node, ast.Name) and "lock" in node.id.lower():
        return f"{module}:{node.id}"
    return None


class _FuncLocks(ast.NodeVisitor):
    """Per-function: edges between nested with-locks, the set of locks
    acquired anywhere, and (held-lock -> called function keys)."""

    def __init__(self, module: str, cls: Optional[str], defs: Set[str]):
        self.module = module
        self.cls = cls
        self.defs = defs
        self.held: List[str] = []
        self.edges: List[Tuple[str, str, int]] = []
        self.acquired: Set[str] = set()
        self.calls_under: List[Tuple[str, str, int]] = []  # (lock, key, ln)
        self.calls: Set[str] = set()

    def visit_With(self, node: ast.With) -> None:
        entered = 0
        for item in node.items:
            name = _lock_name(item.context_expr, self.module, self.cls)
            if name:
                for h in self.held:
                    self.edges.append((h, name, node.lineno))
                self.held.append(name)
                self.acquired.add(name)
                entered += 1
        self.generic_visit(node)
        for _ in range(entered):
            self.held.pop()

    visit_AsyncWith = visit_With

    def visit_Call(self, node: ast.Call) -> None:
        key = None
        fn = node.func
        if isinstance(fn, ast.Name) and fn.id in self.defs:
            key = fn.id
        elif (isinstance(fn, ast.Attribute)
              and isinstance(fn.value, ast.Name)
              and fn.value.id == "self" and self.cls
              and f"{self.cls}.{fn.attr}" in self.defs):
            key = f"{self.cls}.{fn.attr}"
        if key:
            self.calls.add(key)
            for h in self.held:
                self.calls_under.append((h, key, node.lineno))
        self.generic_visit(node)

    # nested defs get their own pass; don't double-count their bodies
    def visit_FunctionDef(self, node):
        pass

    def visit_AsyncFunctionDef(self, node):
        pass


class LockOrderRule(Rule):
    name = "lock-order"
    description = ("the static lock graph built from 'with lock:' "
                   "nesting (plus same-module call closure) must have "
                   "no cycles")

    def __init__(self) -> None:
        # edge -> first (path, line) witnessing it
        self._edges: Dict[Tuple[str, str], Tuple[str, int]] = {}

    def check(self, sf: SourceFile, ctx: Context) -> Iterable[Finding]:
        if not sf.is_python or "/analysis/" in sf.relpath:
            return ()
        module = sf.relpath.rsplit("/", 1)[-1][:-3]
        defs: Set[str] = set()
        funcs: List[Tuple[str, Optional[str], ast.AST]] = []
        for node in sf.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.add(node.name)
                funcs.append((node.name, None, node))
            elif isinstance(node, ast.ClassDef):
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        defs.add(f"{node.name}.{item.name}")
                        funcs.append((item.name, node.name, item))
        summaries: Dict[str, _FuncLocks] = {}
        for name, cls, node in funcs:
            v = _FuncLocks(module, cls, defs)
            for child in node.body:
                v.visit(child)
            key = f"{cls}.{name}" if cls else name
            summaries[key] = v
        # locks-acquired closure over same-module calls
        reach: Dict[str, Set[str]] = {
            k: set(v.acquired) for k, v in summaries.items()}
        changed = True
        while changed:
            changed = False
            for k, v in summaries.items():
                for callee in v.calls:
                    extra = reach.get(callee, set()) - reach[k]
                    if extra:
                        reach[k].update(extra)
                        changed = True
        for key, v in summaries.items():
            for a, b, line in v.edges:
                self._edges.setdefault((a, b), (sf.relpath, line))
            for held, callee, line in v.calls_under:
                for b in reach.get(callee, ()):
                    if b != held:
                        self._edges.setdefault((held, b),
                                               (sf.relpath, line))
        return ()

    def finalize(self, ctx: Context) -> Iterable[Finding]:
        graph: Dict[str, Set[str]] = {}
        for (a, b) in self._edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        findings: List[Finding] = []
        for cycle in self._cycles(graph):
            members = set(cycle)
            first = min((loc for (a, b), loc in self._edges.items()
                         if a in members and b in members),
                        default=None)
            if first is None:
                continue
            path, line = first
            order = " -> ".join(cycle + (cycle[0],))
            findings.append(Finding(
                self.name, path, line,
                f"potential lock-order cycle: {order} — two paths can "
                "acquire these locks in opposite orders and deadlock"))
        self._edges.clear()
        return findings

    @staticmethod
    def _cycles(graph: Dict[str, Set[str]]) -> List[Tuple[str, ...]]:
        """Elementary cycles via Tarjan SCCs (every SCC with an edge
        inside it is reported as one canonical cycle)."""
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on_stack: Set[str] = set()
        stack: List[str] = []
        counter = [0]
        sccs: List[List[str]] = []

        def strongconnect(v: str) -> None:
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on_stack.add(v)
            for w in sorted(graph.get(v, ())):
                if w not in index:
                    strongconnect(w)
                    low[v] = min(low[v], low[w])
                elif w in on_stack:
                    low[v] = min(low[v], index[w])
            if low[v] == index[v]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == v:
                        break
                sccs.append(scc)

        for v in sorted(graph):
            if v not in index:
                strongconnect(v)
        out: List[Tuple[str, ...]] = []
        for scc in sccs:
            if len(scc) > 1:
                out.append(tuple(sorted(scc)))
            elif scc and scc[0] in graph.get(scc[0], ()):
                out.append((scc[0],))
        return out
