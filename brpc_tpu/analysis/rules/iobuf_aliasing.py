"""iobuf-aliasing: an IOBuf handed to the write path must not be
mutated afterwards.

``socket.write(buf)`` enqueues buf's blocks by reference onto the
socket's MPSC write queue; ``append_user_data`` / ``append_buf``
splice the CALLER's object in zero-copy. From that point the writer
fiber and the caller alias the same blocks — a subsequent ``append``/
``clear``/``pop_front``/``cut`` on the caller's name races the wire
bytes (the reference's IOBuf ownership discipline: what you hand to
Socket::Write you no longer own, socket.cpp StartWrite).

Detection is a per-function may-analysis over the statement tree:
after a name is passed to a handoff call (write / write_small /
write_device_payload, or as the argument of append_user_data /
append_buf), any mutating method call on that same name is a finding
until the name is rebound. Disjoint ``if``/``else`` branches do not
poison each other (no false positive on mutually exclusive paths, but
a handoff on EITHER branch poisons the join); loop bodies are scanned
twice with loop-carried state, so a handoff late in iteration N is
seen by the mutation at the top of iteration N+1 — the canonical
``for chunk: buf.append(chunk); sock.write(buf)`` race.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Set, Tuple

from brpc_tpu.analysis.core import Context, Finding, Rule, SourceFile

HANDOFF_METHODS = ("write", "write_small", "write_device_payload")
ALIASING_APPENDS = ("append_user_data", "append_buf")
MUTATORS = ("append", "append_user_data", "append_buf", "clear",
            "pop_front", "cut", "cut_all", "cut_into")


class IOBufAliasingRule(Rule):
    name = "iobuf-aliasing"
    description = ("no mutation of a buffer after it was handed to the "
                   "socket write path or spliced zero-copy into "
                   "another buffer")

    def check(self, sf: SourceFile, ctx: Context) -> Iterable[Finding]:
        if not sf.is_python or "/analysis/" in sf.relpath:
            return ()
        findings: List[Finding] = []
        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                findings.extend(self._scan_function(sf, node))
        return findings

    def _scan_function(self, sf: SourceFile,
                       func: ast.AST) -> List[Finding]:
        findings: List[Finding] = []
        seen: Set[Tuple[int, str, str]] = set()

        def emit(lineno: int, name: str, detail: str, via: str) -> None:
            # loop bodies are scanned twice: dedup by location
            key = (lineno, name, detail)
            if key in seen:
                return
            seen.add(key)
            findings.append(Finding(
                self.name, sf.relpath, lineno,
                f"'{name}.{detail}()' mutates a buffer already "
                f"handed off via '{via}' — the write path "
                "aliases its blocks zero-copy; build a fresh buffer "
                "instead"))

        def apply_expr(node: ast.AST, handed: Dict[str, str]) -> None:
            """Events of one simple statement/expression, in source
            order (handoffs poison a name, rebinding heals it)."""
            events = []   # (lineno, col, kind, name, detail)
            for sub in ast.walk(node):
                if isinstance(sub, ast.Assign):
                    for tgt in sub.targets:
                        if isinstance(tgt, ast.Name):
                            events.append((sub.lineno, sub.col_offset,
                                           "rebind", tgt.id, ""))
                elif isinstance(sub, ast.Call) and isinstance(
                        sub.func, ast.Attribute):
                    attr = sub.func.attr
                    if attr in HANDOFF_METHODS or attr in ALIASING_APPENDS:
                        for arg in sub.args:
                            if isinstance(arg, ast.Name):
                                events.append(
                                    (sub.lineno, sub.col_offset,
                                     "handoff", arg.id, attr))
                    if attr in MUTATORS and isinstance(sub.func.value,
                                                       ast.Name):
                        events.append((sub.lineno, sub.col_offset,
                                       "mutate", sub.func.value.id, attr))
            events.sort(key=lambda e: (e[0], e[1]))
            for lineno, _col, kind, name, detail in events:
                if kind == "rebind":
                    handed.pop(name, None)
                elif kind == "handoff":
                    handed[name] = detail
                elif kind == "mutate" and name in handed:
                    emit(lineno, name, detail, handed[name])

        def scan_stmts(stmts, handed: Dict[str, str]) -> None:
            for st in stmts:
                if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef)):
                    continue   # nested defs are scanned as their own funcs
                if isinstance(st, ast.If):
                    apply_expr(st.test, handed)
                    # disjoint branches: neither poisons the other, but
                    # a handoff on EITHER poisons the join (may-analysis)
                    h_body, h_else = dict(handed), dict(handed)
                    scan_stmts(st.body, h_body)
                    scan_stmts(st.orelse, h_else)
                    handed.clear()
                    handed.update(h_else)
                    handed.update(h_body)
                elif isinstance(st, (ast.For, ast.AsyncFor, ast.While)):
                    apply_expr(st.iter if isinstance(
                        st, (ast.For, ast.AsyncFor)) else st.test, handed)
                    # two-iteration unroll: a handoff late in the body
                    # aliases the mutation at the top of the NEXT pass
                    h = dict(handed)
                    scan_stmts(st.body, h)
                    scan_stmts(st.body, h)
                    scan_stmts(st.orelse, h)
                    handed.update(h)   # join with the zero-iteration path
                elif isinstance(st, (ast.With, ast.AsyncWith)):
                    for item in st.items:
                        apply_expr(item.context_expr, handed)
                    scan_stmts(st.body, handed)
                elif isinstance(st, ast.Try):
                    scan_stmts(st.body, handed)
                    for handler in st.handlers:
                        h = dict(handed)
                        scan_stmts(handler.body, h)
                        handed.update(h)
                    scan_stmts(st.orelse, handed)
                    scan_stmts(st.finalbody, handed)
                else:
                    apply_expr(st, handed)

        scan_stmts(func.body, {})
        return findings
