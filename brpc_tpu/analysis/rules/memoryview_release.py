"""memoryview-release: a view of a resizable buffer is released on
every path before the buffer is resized.

The PR 6 BufferError, distilled: ``mv = memoryview(self._wirebuf)``
followed by ``del self._wirebuf[:n]`` is only correct if ``mv`` is
RELEASED first — a refcount-implicit release is not enough, because a
frame-walking sampler (the flight recorder holding another thread's
frame during a sample) briefly pins the frame's locals and keeps the
view alive, turning the resize into ``BufferError: Existing exports of
data``. The discipline: ``try: ... finally: mv.release()`` (or
``with memoryview(buf) as mv:``) before any resize of the source.

Scope: within one function, a ``memoryview(X)`` of a Name or
``self.attr`` source followed (in execution order) by a resize of the
same source — ``del X[...]``, ``X += ...``, ``X.clear()/.extend()/
.append()/.pop()/.popleft()/.resize()/.truncate()`` — must have an
unconditional ``mv.release()`` between the two. A release inside a
conditional branch does not cover (the other path leaks the export); a
release in a ``finally`` covers everything after its try; the
``with memoryview(...)`` form releases at block exit.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Tuple

from brpc_tpu.analysis.core import Context, Finding, Rule, SourceFile

_RESIZE_METHODS = frozenset(("clear", "extend", "append", "pop",
                             "popleft", "resize", "truncate"))


def _src_key(node: ast.AST) -> Optional[str]:
    """Canonical name of a view-source expression: Name or self.attr."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return f"self.{node.attr}"
    return None


class _Linearizer:
    """Flatten a function body into execution-ordered events, with a
    branch-context tuple per event so conditional releases don't cover
    unconditional mutations. try bodies count as unconditional (the
    happy path runs them in order); If/else/except bodies are branches;
    a ``finally`` suite is emitted after its try (it runs before
    anything that follows)."""

    def __init__(self):
        self.events: List[tuple] = []   # (kind, *data, branch_ctx)
        self._pos = 0
        self._branch: Tuple[int, ...] = ()

    def pos(self) -> int:
        self._pos += 1
        return self._pos

    def emit(self, kind: str, *data) -> None:
        self.events.append((kind, self.pos(), self._branch) + data)

    def walk_body(self, body: List[ast.stmt]) -> None:
        for stmt in body:
            self.walk_stmt(stmt)

    def walk_stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            return
        if isinstance(stmt, ast.Try):
            self.walk_body(stmt.body)
            old = self._branch
            for i, h in enumerate(stmt.handlers):
                self._branch = old + (id(h) % 9973,)
                self.walk_body(h.body)
            self._branch = old
            self.walk_body(stmt.orelse)
            self.walk_body(stmt.finalbody)
            return
        if isinstance(stmt, ast.If):
            old = self._branch
            self._branch = old + (stmt.lineno,)
            self.walk_body(stmt.body)
            self._branch = old + (-stmt.lineno,)
            self.walk_body(stmt.orelse)
            self._branch = old
            return
        if isinstance(stmt, (ast.While, ast.For)):
            self.walk_body(stmt.body)
            self.walk_body(stmt.orelse)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            withviews = []
            for item in stmt.items:
                ce = item.context_expr
                if isinstance(ce, ast.Call) and \
                        isinstance(ce.func, ast.Name) and \
                        ce.func.id == "memoryview" and ce.args:
                    src = _src_key(ce.args[0])
                    var = None
                    if isinstance(item.optional_vars, ast.Name):
                        var = item.optional_vars.id
                    if src and var:
                        self.emit("view", var, src, ce.lineno)
                        withviews.append(var)
            self.walk_body(stmt.body)
            for var in withviews:     # __exit__ releases the export
                self.emit("release", var)
            return
        self.scan_expr_stmt(stmt)

    def scan_expr_stmt(self, stmt: ast.stmt) -> None:
        # view creation: mv = memoryview(src)
        if isinstance(stmt, ast.Assign) and \
                isinstance(stmt.value, ast.Call) and \
                isinstance(stmt.value.func, ast.Name) and \
                stmt.value.func.id == "memoryview" and stmt.value.args:
            src = _src_key(stmt.value.args[0])
            if src:
                for tgt in stmt.targets:
                    if isinstance(tgt, ast.Name):
                        self.emit("view", tgt.id, src, stmt.lineno)
            return
        # del src[...] — the resize
        if isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                if isinstance(t, ast.Subscript):
                    src = _src_key(t.value)
                    if src:
                        self.emit("mutate", src, stmt.lineno,
                                  "del %s[...]" % src)
            return
        # src += ...
        if isinstance(stmt, ast.AugAssign):
            src = _src_key(stmt.target)
            if src:
                self.emit("mutate", src, stmt.lineno, f"{src} += ...")
            return
        # re-binding the view var or the source kills the old export
        if isinstance(stmt, ast.Assign):
            for tgt in stmt.targets:
                if isinstance(tgt, ast.Name):
                    self.emit("rebind", tgt.id)
                src = _src_key(tgt)
                if src:
                    self.emit("rebind_src", src)
        # mv.release() / src.clear() etc. anywhere in the statement
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if not isinstance(fn, ast.Attribute):
                continue
            if fn.attr == "release" and isinstance(fn.value, ast.Name):
                self.emit("release", fn.value.id)
            elif fn.attr in _RESIZE_METHODS:
                src = _src_key(fn.value)
                if src:
                    self.emit("mutate", src, node.lineno,
                              f"{src}.{fn.attr}()")


class MemoryviewReleaseRule(Rule):
    name = "memoryview-release"
    description = ("a memoryview of a resizable buffer must be "
                   "released (finally: mv.release() / with-form) "
                   "before the buffer is resized — a frame-pinning "
                   "sampler otherwise turns the resize into "
                   "BufferError")

    def check(self, sf: SourceFile, ctx: Context) -> Iterable[Finding]:
        if not sf.is_python or "/analysis/" in sf.relpath:
            return ()
        findings: List[Finding] = []
        for node in ast.walk(sf.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            lin = _Linearizer()
            lin.walk_body(node.body)
            findings.extend(self._scan(sf, lin.events))
        return findings

    def _scan(self, sf: SourceFile, events: List[tuple]) -> List[Finding]:
        out: List[Finding] = []
        # live views: var -> (src, viewline, view_pos, view_branch)
        live = {}
        for ev in events:
            kind = ev[0]
            if kind == "view":
                _, pos, branch, var, src, line = ev
                live[var] = (src, line, pos, branch)
            elif kind in ("release", "rebind"):
                _, pos, branch, var = ev
                t = live.get(var)
                # a release buried in a conditional branch only covers
                # paths through that branch: it clears the view only
                # when it is at the view's own (or an outer) branch
                # level — prefix-equal contexts
                if t is not None and t[3][:len(branch)] == branch[
                        :len(t[3])] and len(branch) <= len(t[3]):
                    live.pop(var, None)
            elif kind == "rebind_src":
                _, pos, branch, src = ev
                for var in [v for v, t in live.items() if t[0] == src]:
                    t = live[var]
                    if t[3][:len(branch)] == branch[:len(t[3])] and \
                            len(branch) <= len(t[3]):
                        live.pop(var, None)
            elif kind == "mutate":
                _, pos, branch, src, line, desc = ev
                for var, (vsrc, vline, vpos, vbranch) in list(
                        live.items()):
                    if vsrc != src:
                        continue
                    # skip only DIVERGENT branches (then vs else): a
                    # mutation in an outer/unconditional context after
                    # a branch-local view IS on the view's path (the
                    # branch was taken, the view leaked out of it), and
                    # a mutation deeper inside the view's branch is too
                    n = min(len(vbranch), len(branch))
                    if vbranch[:n] != branch[:n]:
                        continue
                    out.append(Finding(
                        self.name, sf.relpath, line,
                        f"{desc} while memoryview '{var}' (taken at "
                        f"line {vline}) may still export the buffer — "
                        "a frame-pinning sampler keeps the view alive "
                        "and the resize raises BufferError; release "
                        "the view first (try/finally or the with-"
                        "statement form)"))
                    live.pop(var, None)   # one report per view
        return out
