"""event-wait-not-sleep: a long-lived thread loop paces itself with
``Event.wait(timeout)``, never ``time.sleep``.

The PR 6 lesson, twice over: (1) ``stop()`` cannot interrupt a sleep —
shutdown waits out the tail of whatever nap the loop is in (the
spawn_util watchdog and the shard monitor both shipped this); (2) the
flight recorder's idle classification keys on the leaf frame —
``Event.wait`` parks in ``threading.py`` and classifies idle, while
``time.sleep`` shows up as an opaque busy-ish leaf that pollutes the
flamegraph. The fix is mechanical: give the loop a ``threading.Event``
and ``wait(period)`` on it; ``stop()`` sets it.

A root is any function handed to ``threading.Thread(target=...)``; the
rule walks its same-module call closure and flags ``time.sleep`` calls
sitting inside a ``while`` loop.
"""

from __future__ import annotations

from typing import Iterable, List, Set

from brpc_tpu.analysis.core import Context, Finding, Rule, SourceFile
from brpc_tpu.analysis.lockmodel import get_lock_model


class EventWaitNotSleepRule(Rule):
    name = "event-wait-not-sleep"
    description = ("time.sleep in a long-lived thread loop must be "
                   "Event.wait(timeout): stop() can interrupt it and "
                   "the profiler classifies the thread idle")

    def finalize(self, ctx: Context) -> Iterable[Finding]:
        model = get_lock_model(ctx)
        roots: Set[str] = {fkey for _, fkey, _, _ in model.thread_roots}
        findings: List[Finding] = []
        reported: Set[tuple] = set()
        for root in sorted(roots):
            for info, chain in model.same_module_closure(root):
                for line in info.sleeps_in_loop:
                    if (info.relpath, line) in reported:
                        continue
                    reported.add((info.relpath, line))
                    via = ("" if len(chain) == 1 else
                           " (reached via " + " -> ".join(
                               c.split("::")[-1] for c in chain) + ")")
                    findings.append(Finding(
                        self.name, info.relpath, line,
                        f"time.sleep() paces the thread loop "
                        f"'{info.qual}'{via} — use threading.Event."
                        "wait(timeout) so stop() can interrupt the nap "
                        "and the flight recorder classifies the thread "
                        "idle"))
        return findings
