"""The three whole-program lock rules riding the lock model
(``analysis/lockmodel.py``): lock-cycle, callback-under-lock and
blocking-under-lock.

These are graftlint v2's replacement for the v1 intramodule
``lock-order`` rule: the same AB/BA-deadlock check, but over the
INTERPROCEDURAL acquisition graph (a ``with A:`` around a call whose
callee — possibly in another module — takes B is an A->B edge), plus
the two held-context rules whose violations this repo has fixed by
hand in PR 7 (attempt records under ``_arb_lock``/``_lb_lock``) and
PR 8 (batcher callbacks fired under the batcher lock).
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Tuple

from brpc_tpu.analysis.core import Context, Finding, Rule, SourceFile
from brpc_tpu.analysis.lockmodel import get_lock_model

# modules that ARE the blocking layer: the fiber runtime's pthread side
# legitimately parks carrier threads under its own coordination locks
# (parking lots, stack pools); everything above it must not
_BLOCKING_ALLOWLIST = (
    "brpc_tpu/fiber/scheduler.py",
    "brpc_tpu/fiber/butex.py",
    "brpc_tpu/fiber/timer.py",
    "brpc_tpu/fiber/stacks.py",
    "brpc_tpu/fiber/execution_queue.py",
    "brpc_tpu/fiber/worker_module.py",
)


class LockCycleRule(Rule):
    name = "lock-cycle"
    description = ("the whole-program lock acquisition graph (with-"
                   "nesting plus interprocedural call edges) must be "
                   "acyclic; reports the witness path")

    def finalize(self, ctx: Context) -> Iterable[Finding]:
        model = get_lock_model(ctx)
        findings: List[Finding] = []
        for cycle in model.cycles():
            members = set(cycle)
            # witness: one concrete edge location per hop of the cycle
            hops: List[str] = []
            first: Optional[Tuple[str, int]] = None
            for (a, b), (path, line, chain) in sorted(
                    model.edges.items()):
                if a in members and b in members:
                    via = (f" via {'->'.join(c.split('::')[-1] for c in chain)}"
                           if len(chain) > 1 else "")
                    hops.append(f"{a}->{b} at {path}:{line}{via}")
                    if first is None:
                        first = (path, line)
            if first is None:
                continue
            order = " -> ".join(cycle + (cycle[0],))
            findings.append(Finding(
                self.name, first[0], first[1],
                f"lock acquisition cycle: {order} — two paths can take "
                f"these locks in opposite orders and deadlock; "
                f"witness: {'; '.join(hops[:4])}"))
        return findings


class CallbackUnderLockRule(Rule):
    name = "callback-under-lock"
    description = ("no stored callback / user hook / socket write may "
                   "run while a framework lock is held (the callback "
                   "can re-enter the locked subsystem or block it)")

    def finalize(self, ctx: Context) -> Iterable[Finding]:
        model = get_lock_model(ctx)
        findings: List[Finding] = []
        for info in model.funcs.values():
            inherited = model.under_locks.get(info.key, set())
            for line, desc, held in info.callbacks:
                locks = set(held) | inherited
                if not locks:
                    continue
                if held:
                    how = f"while holding {', '.join(sorted(held))}"
                else:
                    chain = model.witness_chain(info.key)
                    how = (f"reached under {', '.join(sorted(locks))} "
                           f"(via {' -> '.join(c.split('::')[-1] for c in chain)})")
                findings.append(Finding(
                    self.name, info.relpath, line,
                    f"{desc} invoked {how} in '{info.qual}' — "
                    "callbacks re-enter the framework (socket failure "
                    "paths call cancel(), hooks take their own locks); "
                    "collect under the lock, fire after releasing it"))
        return findings


class BlockingUnderLockRule(Rule):
    name = "blocking-under-lock"
    description = ("no blocking operation (time.sleep, Event.wait, "
                   "blocking socket ops, subprocess) may run while a "
                   "framework lock is held")

    def finalize(self, ctx: Context) -> Iterable[Finding]:
        model = get_lock_model(ctx)
        findings: List[Finding] = []
        for info in model.funcs.values():
            if info.relpath.endswith(_BLOCKING_ALLOWLIST):
                continue
            inherited = model.under_locks.get(info.key, set())
            for line, why, held in info.blocking:
                locks = set(held) | inherited
                if not locks:
                    continue
                if held:
                    how = f"while holding {', '.join(sorted(held))}"
                else:
                    chain = model.witness_chain(info.key)
                    how = (f"reached under {', '.join(sorted(locks))} "
                           f"(via {' -> '.join(c.split('::')[-1] for c in chain)})")
                findings.append(Finding(
                    self.name, info.relpath, line,
                    f"{why} {how} in '{info.qual}' — every other "
                    "thread/fiber contending that lock stalls for the "
                    "whole wait; move the wait outside the critical "
                    "section"))
        return findings
