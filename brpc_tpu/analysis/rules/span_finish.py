"""span-finish: every started rpcz span must reach finish_span.

A Span created by ``start_server_span``/``start_client_span`` is only
visible once ``finish_span`` submits it — a path that returns (or
raises) without finishing silently drops exactly the spans operators
grep /rpcz for (sheds, parse errors, dead peers). The rule walks every
function that starts a span with a small path-sensitive interpreter:
along each path to an exit (``return``/``raise``/fall-through), either
a direct ``finish_span(...)`` call must have executed, or a *deferred*
finish must have been registered — a lambda/def whose body calls
``finish_span``, the completion-hook idiom Channel.call uses (the hook
runs on every completion path, so registering it satisfies all later
exits).

A ``try`` whose ``finally`` finishes covers every exit inside it; a
span started inside one branch of an ``if`` taints the merged path
(the other branch typically binds a null-span stand-in and calls the
same ``finish_span`` alias, which the rule sees textually).
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from brpc_tpu.analysis.core import Context, Finding, Rule, SourceFile

_START_NAMES = ("start_server_span", "start_client_span")
_FINISH = "finish_span"


def _call_name(node: ast.Call) -> str:
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return ""


def _iter_shallow(stmt: ast.AST):
    """AST nodes of one statement, NOT descending into nested function
    or lambda bodies (their control flow is not this function's)."""
    stack = [stmt]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            stack.append(child)


def _stmt_starts(stmt: ast.AST) -> bool:
    return any(isinstance(n, ast.Call) and _call_name(n) in _START_NAMES
               for n in _iter_shallow(stmt))


def _stmt_finishes(stmt: ast.AST) -> bool:
    return any(isinstance(n, ast.Call) and _call_name(n) == _FINISH
               for n in _iter_shallow(stmt))


def _stmt_defers_finish(stmt: ast.AST) -> bool:
    """A lambda/def registered in this statement whose body calls
    finish_span: the completion-hook pattern — once registered, the
    hook finishes the span on every completion path."""
    for node in ast.walk(stmt):
        if isinstance(node, (ast.Lambda, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            body = node.body if isinstance(node.body, list) else [node.body]
            for sub in body:
                for n in ast.walk(sub):
                    if isinstance(n, ast.Call) and _call_name(n) == _FINISH:
                        return True
    return False


class _State:
    __slots__ = ("started", "finished")

    def __init__(self, started: bool = False, finished: bool = False):
        self.started = started
        self.finished = finished

    def copy(self) -> "_State":
        return _State(self.started, self.finished)

    @property
    def leaky(self) -> bool:
        return self.started and not self.finished


class SpanFinishRule(Rule):
    name = "span-finish"
    description = ("every start_server_span/start_client_span call site "
                   "must reach finish_span (direct or via a registered "
                   "completion hook) on all paths")

    def check(self, sf: SourceFile, ctx: Context) -> Iterable[Finding]:
        if not sf.is_python or sf.tree is None \
                or "/analysis/" in sf.relpath \
                or sf.relpath.endswith("rpc/span.py"):
            return ()
        findings: List[Finding] = []
        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if any(_stmt_starts(s) for s in node.body):
                    self._analyze(sf, node, findings)
        return findings

    # ---------------------------------------------------------- analysis
    def _analyze(self, sf: SourceFile, fn, findings: List[Finding]) -> None:
        st = _State()
        terminated = self._walk(sf, fn.body, st, findings)
        if not terminated and st.leaky:
            findings.append(self._finding(
                sf, fn.body[-1].lineno,
                f"function '{fn.name}' can fall off its end"))

    def _finding(self, sf: SourceFile, line: int, how: str) -> Finding:
        return Finding(
            self.name, sf.relpath, line,
            f"{how} with a started span never passed to finish_span — "
            "the span (and its error/stage record) is silently dropped")

    def _walk(self, sf: SourceFile, stmts, st: _State,
              findings: List[Finding]) -> bool:
        """Interpret a statement list; returns True when every path
        through it terminated (return/raise/continue/break)."""
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if _stmt_defers_finish(stmt):
                    st.finished = True
                continue
            if not isinstance(stmt, (ast.If, ast.For, ast.AsyncFor,
                                     ast.While, ast.Try, ast.With,
                                     ast.AsyncWith)):
                # simple statement: start/finish effects apply directly;
                # compound statements get them branch-by-branch below
                if _stmt_finishes(stmt) or _stmt_defers_finish(stmt):
                    st.finished = True
                if _stmt_starts(stmt):
                    st.started = True
                    if not _stmt_finishes(stmt):
                        st.finished = False   # a fresh span, a fresh finish
            if isinstance(stmt, ast.Return):
                if st.leaky:
                    findings.append(self._finding(
                        sf, stmt.lineno, "path returns"))
                return True
            if isinstance(stmt, ast.Raise):
                if st.leaky:
                    findings.append(self._finding(
                        sf, stmt.lineno, "path raises"))
                return True
            if isinstance(stmt, (ast.Break, ast.Continue)):
                return True        # stays inside the function: not a leak
            if isinstance(stmt, ast.If):
                s_body, s_else = st.copy(), st.copy()
                t_body = self._walk(sf, stmt.body, s_body, findings)
                t_else = self._walk(sf, stmt.orelse, s_else, findings)
                live = [s for s, t in ((s_body, t_body), (s_else, t_else))
                        if not t]
                if not live:
                    return True
                st.started = any(s.started for s in live)
                st.finished = all(s.finished or not s.started for s in live)
            elif isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                s_body = st.copy()
                self._walk(sf, stmt.body, s_body, findings)
                if stmt.orelse:
                    self._walk(sf, stmt.orelse, st.copy(), findings)
                # zero-iteration conservatism: the loop can only add
                # starts, never satisfy an outer finish — and a span
                # the body starts without finishing taints the merged
                # path (it leaks on every iteration)
                st.started = st.started or s_body.started
                if s_body.leaky:
                    st.finished = False
            elif isinstance(stmt, ast.Try):
                if self._walk_try(sf, stmt, st, findings):
                    return True
                if self._finally_finishes(stmt):
                    st.finished = True
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                if self._walk(sf, stmt.body, st, findings):
                    return True
        return False

    def _finally_finishes(self, stmt: ast.Try) -> bool:
        return any(_stmt_finishes(s) or _stmt_defers_finish(s)
                   for s in stmt.finalbody)

    def _walk_try(self, sf: SourceFile, stmt: ast.Try, st: _State,
                  findings: List[Finding]) -> bool:
        """Returns True when every path through the try terminated."""
        fin = self._finally_finishes(stmt)
        s_body = st.copy()
        s_body.finished = s_body.finished or fin   # every exit runs finally
        t_body = self._walk(sf, stmt.body, s_body, findings)
        if not t_body and stmt.orelse:
            t_body = self._walk(sf, stmt.orelse, s_body, findings)
        live = [] if t_body else [s_body]
        for handler in stmt.handlers:
            s_h = st.copy()
            # the handler may observe any prefix of the body: a span
            # started in the body counts as started here
            s_h.started = s_h.started or s_body.started
            s_h.finished = s_h.finished or fin
            if not self._walk(sf, handler.body, s_h, findings):
                live.append(s_h)
        self._walk(sf, stmt.finalbody, st.copy(), findings)
        if not live:
            # all paths inside terminated; the finally itself was checked
            st.started = st.started or s_body.started
            return True
        st.started = any(s.started for s in live)
        st.finished = all(s.finished or not s.started for s in live)
        return False
