"""guarded-by: whole-program lockset guard inference (Eraser-style).

For every class/module field the lock model recorded access sites for,
infer the guard as the lock held at >= 90% of non-constructor write
sites (two or more writes required — one site is not a convention).
Then:

* a write site executed without the inferred guard is a [CONFIRMED]
  finding — the field's own discipline says this site races;
* a read site without the guard is flagged [PLAUSIBLE] only when its
  thread roles are disjoint from every writer role — same-thread
  reads and the repo's deliberate lock-free peek idioms stay quiet;
* a field with NO inferred guard is flagged (once, at its first write
  site) only when its writes span multiple thread roles — the
  cross-role unguarded write, ranked highest because two different
  threads mutate it with no common lock;
* a field written only from one single-thread role (dispatcher tick,
  timer, a sampler, postfork child...) is thread-confined: exempt, and
  published as such in the registry.

Effective locks at a site are the locks held lexically PLUS every lock
possibly held by callers (`under_locks`) — a generous may-analysis, so
a finding here means NO caller path supplies the guard. Lock and
Event attributes themselves are skipped (they synchronize, they are
not synchronized). Waive deliberate lock-free idioms with
``# graftlint: disable=guarded-by -- reason``.

The inferred field->guard registry is published in docs/invariants.md
("Field guards") and snapshot-pinned by test; `python -m
brpc_tpu.analysis --field-guards` regenerates it.
"""

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from brpc_tpu.analysis.core import Context, Finding, Rule
from brpc_tpu.analysis.lockmodel import LockModel, get_lock_model
from brpc_tpu.analysis.threadmodel import (
    EXTERNAL, SINGLE_THREAD_ROLES, ThreadModel, get_thread_model,
)

#: Functions whose writes are construction, not publication.
_INIT_FUNCS = frozenset(("__init__", "__new__", "__post_init__",
                         "__init_subclass__"))
_GUARD_PCT = 0.90
_MIN_WRITES = 2


class _Site:
    """One field access: where, which lock set, which thread roles."""

    __slots__ = ("kind", "fkey", "relpath", "line", "held", "roles")

    def __init__(self, kind: str, fkey: str, relpath: str, line: int,
                 held: frozenset, roles: Set[str]):
        self.kind = kind
        self.fkey = fkey
        self.relpath = relpath
        self.line = line
        self.held = held
        self.roles = roles


def _tls_classes(ctx: Context) -> Set[str]:
    """Classes deriving threading.local: every instance is per-thread,
    so their fields are thread-confined by construction."""
    out: Set[str] = set()
    for sf in ctx.files:
        if not sf.is_python or sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            for base in node.bases:
                if (isinstance(base, ast.Attribute)
                        and base.attr == "local") or \
                        (isinstance(base, ast.Name)
                         and base.id == "local"):
                    out.add(node.name)
    return out


def _collect(ctx: Context) -> Tuple[
        LockModel, ThreadModel,
        Dict[str, Tuple[List[_Site], List[_Site]]]]:
    """field -> (write sites, read sites), constructor bodies dropped."""
    model = get_lock_model(ctx)
    tm = get_thread_model(ctx)
    fields: Dict[str, Tuple[List[_Site], List[_Site]]] = {}
    for fkey, info in model.funcs.items():
        if not info.attr_uses:
            continue
        if info.qual.split(".")[-1] in _INIT_FUNCS:
            continue
        under = frozenset(model.under_locks.get(fkey, ()))
        roles = tm.roles_of(fkey)
        if roles == {"postfork"}:
            # fork-child-only code (postfork reset handlers): the
            # child is single-threaded — nothing to race with, and
            # its re-init writes must not poison guard inference
            continue
        for kind, field, line, held in info.attr_uses:
            site = _Site(kind, fkey, info.relpath, line,
                         frozenset(held) | under, roles)
            pair = fields.setdefault(field, ([], []))
            (pair[0] if kind == "w" else pair[1]).append(site)
    return model, tm, fields


def _infer_guard(field: str, writes: List[_Site]) -> Tuple[
        Optional[str], int]:
    """(guard, sites-holding-it) when one lock covers every write site
    (any count) or >= 90% of them (>= _MIN_WRITES sites — a single
    partially-covered site is not a convention); (None, best count)
    otherwise."""
    n = len(writes)
    counts: Dict[str, int] = {}
    for s in writes:
        for lock in s.held:
            counts[lock] = counts.get(lock, 0) + 1
    if not counts:
        return None, 0
    owner = field.rpartition(".")[0]
    # prefer: most write sites covered, then the field's own class
    # lock over a caller's, then stable name order
    best = sorted(counts, key=lambda k: (
        -counts[k], 0 if k.startswith(owner + ".") else 1, k))[0]
    if counts[best] == n or \
            (n >= _MIN_WRITES and counts[best] / n >= _GUARD_PCT):
        return best, counts[best]
    return None, counts[best]


def _race_roles(sites: Iterable[_Site]) -> Set[str]:
    """Roles that can actually interleave: the postfork child runs
    alone in a fresh process, so it races with nothing."""
    roles: Set[str] = set()
    for s in sites:
        roles |= s.roles
    roles.discard("postfork")
    return roles


def _witness(tm: ThreadModel, site: _Site) -> str:
    """' [role: seed -> ... -> site fn]' for the site's best seeded
    role, or a terse external marker."""
    seeded = sorted(r for r in site.roles if r != EXTERNAL)
    for role in seeded:
        chain = tm.chain_for(site.fkey, role)
        if chain:
            return f" [{role}: {chain}]"
    return " [external callers]"


def _confined_role(wroles: Set[str]) -> Optional[str]:
    """The one single-thread role writing the field, if that's all."""
    if len(wroles) == 1:
        role = next(iter(wroles))
        if role in SINGLE_THREAD_ROLES:
            return role
    return None


class GuardedByRule(Rule):
    name = "guarded-by"
    description = ("fields written under an inferred guard (>=90% of "
                   "write sites hold one lock) must hold it at every "
                   "write and at cross-role reads; unguarded fields "
                   "written from multiple thread roles are races")

    def finalize(self, ctx: Context) -> Iterable[Finding]:
        model, tm, fields = _collect(ctx)
        tls = _tls_classes(ctx)
        findings: List[Finding] = []
        for field in sorted(fields):
            writes, reads = fields[field]
            if not writes or field in model.locks:
                continue
            cls, _, attr = field.rpartition(".")
            if cls and (cls in tls or (cls, attr) in model._event_attrs):
                continue
            guard, held_n = _infer_guard(field, writes)
            wroles = _race_roles(writes)
            if guard is not None:
                for s in writes:
                    if guard in s.held:
                        continue
                    findings.append(Finding(
                        self.name, s.relpath, s.line,
                        f"[CONFIRMED] write to {field} without {guard} "
                        f"(guard held at {held_n}/{len(writes)} write "
                        f"sites){_witness(tm, s)}"))
                for s in reads:
                    if guard in s.held:
                        continue
                    rroles = set(s.roles)
                    rroles.discard("postfork")
                    if rroles and wroles and rroles.isdisjoint(wroles):
                        findings.append(Finding(
                            self.name, s.relpath, s.line,
                            f"[PLAUSIBLE] read of {field} without "
                            f"{guard} on {'/'.join(sorted(rroles))} "
                            f"(written under the guard on "
                            f"{'/'.join(sorted(wroles))})"
                            f"{_witness(tm, s)}"))
            elif len(wroles) > 1:
                first = min(writes, key=lambda s: (s.relpath, s.line))
                findings.append(Finding(
                    self.name, first.relpath, first.line,
                    f"[CONFIRMED] cross-role unguarded writes to "
                    f"{field} from {'/'.join(sorted(wroles))} "
                    f"({len(writes)} write sites, no common lock)"
                    f"{_witness(tm, first)}"))
        return findings


# --------------------------------------------------------------- registry
def field_guard_table(ctx: Context) -> List[dict]:
    """The published registry rows: every field with an inferred guard
    plus every thread-confined field, stable order."""
    model, tm, fields = _collect(ctx)
    tls = _tls_classes(ctx)
    rows: List[dict] = []
    for field in sorted(fields):
        writes, _reads = fields[field]
        if not writes or field in model.locks:
            continue
        cls, _, attr = field.rpartition(".")
        if cls and (cls in tls or (cls, attr) in model._event_attrs):
            continue
        guard, held_n = _infer_guard(field, writes)
        if guard is not None:
            rows.append({"field": field, "guard": guard,
                         "writes": len(writes), "held": held_n})
            continue
        role = _confined_role(_race_roles(writes))
        if role is not None:
            rows.append({"field": field, "guard": f"confined:{role}",
                         "writes": len(writes), "held": len(writes)})
    return rows


def render_field_guards(ctx: Context) -> str:
    """Markdown table the docs snapshot pins (and --field-guards
    prints): field | guard | write sites covered."""
    rows = field_guard_table(ctx)
    out = ["| field | guard | writes |",
           "|---|---|---|"]
    for r in rows:
        out.append(f"| `{r['field']}` | `{r['guard']}` "
                   f"| {r['held']}/{r['writes']} |")
    return "\n".join(out)
