"""judge-defer: every native fast lane must judge-or-defer to the
classic lane, and the C++ meta scanners must bound every narrow proto
field they admit.

The framework's fast lanes (scan_frames / serve_scan / pluck_scan /
serve_drain consumers, the turbo dispatch paths, cut-through) follow
one contract: a lane either fully JUDGES a frame with semantics
identical to the classic protobuf path, or DEFERS the verdict to it.
Both ADVICE.md round-5 findings were breaches of exactly this
contract (credits admitted unbounded; need_feedback read-and-dropped),
so the rule encodes it twice:

Python side — any function that consumes a native scanner (calls or
resolves scan_frames/serve_scan/pluck_scan/serve_drain/trpc_scan, or
matches the fast-lane naming conventions) must contain an explicit
defer exit: a ``return None`` / ``return False`` / bare ``return``
statement the classic lane proceeds from.

C++ side — in the native meta walkers (fastcore.cc ``walk_*``
functions, mapped to their tpu_rpc_meta.proto messages), every varint
field case must be faithful:

  * an int32 field read into a 64-bit slot needs an explicit range
    guard (INT32_MAX / 0x7FFFFFFF) or a ``static_cast<int32_t>``
    matching protobuf's truncation — otherwise out-of-range varints
    ride the fast lane with different semantics than the classic
    parser (ADVICE finding 1);
  * a scratch-read field (read into a local, not carried in MetaScan)
    must still be USED after the read — to defer or to gate —
    otherwise the fast lane silently drops wire semantics the classic
    lane preserves (ADVICE finding 2);
  * a DEADLINE field (RpcRequestMeta.timeout_ms) must be enforced or
    deferred, never read-and-ignored: since deadline propagation
    (ISSUE 2) the classic lane stamps arrival and sheds expired
    requests, so a native lane that admits a timeout-bearing request
    without a defer exit after the read serves traffic the classic
    lane would shed — its case block needs a conditional
    ``return false`` (the defer gate) downstream of the read.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Optional, Tuple

from brpc_tpu.analysis.core import Context, Finding, Rule, SourceFile

SCANNER_NAMES = ("scan_frames", "serve_scan", "pluck_scan",
                 "serve_drain", "trpc_scan")
FAST_LANE_NAME_RE = re.compile(
    r"^(turbo_\w+|native_serve|fast_drain|try_cut_through"
    r"|process_\w+_fast|\w*_fast_lane\w*)$")

# native walker -> proto message it decodes (tpu_rpc_meta.proto)
WALKER_MESSAGES = {
    "walk_request_meta": "RpcRequestMeta",
    "walk_response_meta": "RpcResponseMeta",
    "walk_stream_meta": "StreamSettings",
    "walk_meta": "RpcMeta",
}

_NARROW_TYPES = ("int32", "sint32", "sfixed32")
# deadline-class fields: reading one obliges the lane to enforce or
# defer (a conditional `return false` after the read) — see module doc
_DEADLINE_FIELDS = {("RpcRequestMeta", "timeout_ms")}
_DEFER_EXIT_RE = re.compile(r"return\s+false")
_BOUND_RE = re.compile(r"INT32_MAX|0x7FFFFFFF|static_cast<int32_t>")
_CASE_RE = re.compile(r"case\s*\((\d+)u?\s*<<\s*3\)\s*\|\s*0\s*:")
# any switch label bounds a case block — including wiretype-2 cases and
# default:, or the last varint case's "block" swallows the function tail
# and an unrelated bound there satisfies its check
_LABEL_RE = re.compile(r"\bcase\b|\bdefault\s*:")
_READ_RE = re.compile(r"read_varint\(\s*p\s*,\s*end\s*,\s*&([\w>\-\.]+)\s*\)")
_COMMENT_RE = re.compile(r"//[^\n]*|/\*.*?\*/", re.S)


def _strip_comments(text: str) -> str:
    """Blank C++ comments in-place (newlines kept so offsets and line
    math survive): a bound or use mentioned only in a comment — e.g. an
    explanatory ``// must be <= INT32_MAX`` next to a case that lost
    its guard — must not satisfy the checks below."""
    return _COMMENT_RE.sub(lambda m: re.sub(r"[^\n]", " ", m.group(0)),
                           text)


def _parse_proto(text: str) -> Dict[str, Dict[int, Tuple[str, str]]]:
    """message -> {field_number: (type, name)} for scalar fields."""
    out: Dict[str, Dict[int, Tuple[str, str]]] = {}
    cur: Optional[str] = None
    for line in text.splitlines():
        m = re.match(r"\s*message\s+(\w+)\s*{", line)
        if m:
            cur = m.group(1)
            out[cur] = {}
            continue
        if cur and re.match(r"\s*}", line):
            cur = None
            continue
        if cur:
            f = re.match(r"\s*(?:repeated\s+)?(\w+)\s+(\w+)\s*=\s*(\d+)\s*;",
                         line)
            if f:
                out[cur][int(f.group(3))] = (f.group(1), f.group(2))
    return out


class JudgeDeferRule(Rule):
    name = "judge-defer"
    description = ("native fast lanes must defer to the classic lane; "
                   "C++ meta walkers must bound int32 fields and never "
                   "read-and-drop wire semantics")

    # ------------------------------------------------------ python side
    def check(self, sf: SourceFile, ctx: Context) -> Iterable[Finding]:
        if sf.is_python:
            return self._check_python(sf)
        if sf.relpath.endswith(".cc") and "walk_meta" in sf.text:
            return self._check_walkers(sf, ctx)
        return ()

    def _check_python(self, sf: SourceFile) -> Iterable[Finding]:
        if "/analysis/" in sf.relpath:
            return ()
        findings: List[Finding] = []
        for node in ast.walk(sf.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if not self._is_fast_lane(node):
                continue
            if not self._has_defer_exit(node):
                findings.append(Finding(
                    self.name, sf.relpath, node.lineno,
                    f"fast-lane function '{node.name}' has no defer "
                    "exit (return None/False) — a frame the native "
                    "scanner cannot faithfully judge must fall back to "
                    "the classic lane"))
        return findings

    def _is_fast_lane(self, func: ast.AST) -> bool:
        if FAST_LANE_NAME_RE.match(func.name):
            return True
        for node in ast.walk(func):
            if isinstance(node, ast.Call):
                fn = node.func
                name = fn.attr if isinstance(fn, ast.Attribute) else (
                    fn.id if isinstance(fn, ast.Name) else None)
                if name in SCANNER_NAMES:
                    return True
            elif isinstance(node, ast.Constant) \
                    and node.value in SCANNER_NAMES:
                # getattr(fc, "scan_frames", ...)-style resolution
                return True
        return False

    def _has_defer_exit(self, func: ast.AST) -> bool:
        # a defer exit inside a NESTED def (callback/helper) does not
        # return from the fast-lane function itself
        nested = set()
        for node in ast.walk(func):
            if node is not func and isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for sub in ast.walk(node):
                    nested.add(id(sub))
        for node in ast.walk(func):
            if id(node) in nested:
                continue
            if isinstance(node, ast.Return):
                v = node.value
                if v is None:
                    return True
                if isinstance(v, ast.Constant) and (v.value is None or
                                                    v.value is False):
                    return True
                # parse()-shaped defer: return PARSE_TRY_OTHERS, None
                if isinstance(v, ast.Tuple) and v.elts and isinstance(
                        v.elts[0], ast.Name) and v.elts[0].id in (
                            "PARSE_TRY_OTHERS", "PARSE_NOT_ENOUGH_DATA"):
                    return True
        return False

    # --------------------------------------------------------- C++ side
    def _check_walkers(self, sf: SourceFile,
                       ctx: Context) -> Iterable[Finding]:
        proto = self._load_proto(sf)
        if not proto:
            return ()
        text = _strip_comments(sf.text)
        findings: List[Finding] = []
        for walker, message in WALKER_MESSAGES.items():
            fields = proto.get(message)
            body, start_line = self._function_body(text, walker)
            if body is None or fields is None:
                continue
            findings.extend(self._check_cases(sf, walker, message,
                                              fields, body, start_line))
        return findings

    def _load_proto(self, sf: SourceFile) -> Dict:
        # tpu_rpc_meta.proto sits next to the package the .cc belongs to
        root = sf.path
        for _ in range(6):
            root = os.path.dirname(root)
            cand = os.path.join(root, "protocol", "proto",
                                "tpu_rpc_meta.proto")
            if os.path.exists(cand):
                with open(cand, encoding="utf-8") as f:
                    return _parse_proto(f.read())
        return {}

    def _function_body(self, text: str,
                       name: str) -> Tuple[Optional[str], int]:
        """Brace-matched body of ``name(...) {...}`` plus its first
        line number. ``text`` is the comment-stripped source."""
        m = re.search(r"\b" + name + r"\s*\([^)]*\)\s*{", text)
        if not m:
            return None, 0
        depth = 0
        for i in range(m.end() - 1, len(text)):
            c = text[i]
            if c == "{":
                depth += 1
            elif c == "}":
                depth -= 1
                if depth == 0:
                    start_line = text.count("\n", 0, m.start()) + 1
                    return text[m.start():i + 1], start_line
        return None, 0

    def _check_cases(self, sf: SourceFile, walker: str, message: str,
                     fields: Dict[int, Tuple[str, str]], body: str,
                     start_line: int) -> Iterable[Finding]:
        findings: List[Finding] = []
        cases = list(_CASE_RE.finditer(body))
        for cm in cases:
            field_no = int(cm.group(1))
            nxt = _LABEL_RE.search(body, cm.end())
            end = nxt.start() if nxt else len(body)
            block = body[cm.start():end]
            # the case block ends at its break/return run; the slice to
            # the next label is close enough for the checks below
            ftype, fname = fields.get(field_no, ("", ""))
            if not ftype:
                continue
            read = _READ_RE.search(block)
            if not read:
                continue
            target = read.group(1)
            line = start_line + body.count("\n", 0, cm.start())
            after = block[read.end():]
            # the truncation guard `if (!read_varint(...)) return false;`
            # trails every read — its `return false` is not a defer
            # decision about the VALUE, so it must not satisfy the
            # deadline check below
            after_guard = re.sub(r"^\s*\)\s*return\s+false\s*;", "",
                                 after)
            if (message, fname) in _DEADLINE_FIELDS \
                    and not _DEFER_EXIT_RE.search(after_guard):
                findings.append(Finding(
                    self.name, sf.relpath, line,
                    f"{walker}: {message}.{fname} is read without "
                    "either enforcing or deferring — the classic lane "
                    "stamps arrival and sheds expired requests "
                    "(deadline propagation), so this lane needs a "
                    "conditional `return false` after the read (defer "
                    "the frame, or gate it on an enforce-by-"
                    "construction posture like MetaScan.defer_timeout)"))
                continue
            if target.startswith("m->"):
                if ftype in _NARROW_TYPES and not _BOUND_RE.search(block):
                    findings.append(Finding(
                        self.name, sf.relpath, line,
                        f"{walker}: {message}.{fname} is {ftype} but is "
                        "admitted into a 64-bit slot without an "
                        "INT32_MAX bound or static_cast<int32_t> — "
                        "out-of-range varints would ride the fast lane "
                        "with different semantics than the classic "
                        "parser (defer them: return false)"))
            else:
                # scratch read: the value must be used (defer/gate/carry)
                if not re.search(r"\b" + re.escape(target) + r"\b", after):
                    findings.append(Finding(
                        self.name, sf.relpath, line,
                        f"{walker}: {message}.{fname} is read into "
                        f"'{target}' and dropped — wire semantics the "
                        "classic lane preserves are silently discarded "
                        "on the fast lane (defer when set, or carry it "
                        "through the scan record)"))
        return findings
