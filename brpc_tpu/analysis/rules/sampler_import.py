"""sampler-no-lazy-import: no import statement reachable from a
profiler/sampler thread loop.

The PR 8 war story: the flight recorder's attribution path lazily
imported ``worker_module`` — the FIRST execution opens the module file
ON THE SAMPLER THREAD at sample time, a transient fd that appears and
disappears mid-sample. In fd-exhaustion scenarios that open/close
handed the EMFILE accept-backoff test a free descriptor and flaked it
~50%. Sampler-thread code must bind every import before the thread
starts (module load, or an explicit bind step in ``ensure_running``).

A sampler root is a ``threading.Thread(target=...)`` whose class name,
thread name or target name mentions sampling (``sampl``/``record``/
``flight``/``profil``); the rule walks the target's call closure
through the lock model's resolved edges and flags any ``import``
statement executed inside it.

The walk crosses module boundaries by MARKER NAME: a resolved call
leaving the root's module becomes a new root when the callee's own
name matches the markers (the bvar sampler's tick calling
``series_sample_tick`` in bvar/series.py, which calls
``watchdog_sample_pass`` in bvar/anomaly.py — the trend-ring engine
and the anomaly watchdog are sampler-thread code even though the
thread object lives in bvar/window.py). Naming the entrypoint with a
marker is the opt-in; an unmarked cross-module callee stays out of
scope, so helper calls into unrelated modules cannot flood the rule.
"""

from __future__ import annotations

from typing import Iterable, List, Set

from brpc_tpu.analysis.core import Context, Finding, Rule, SourceFile
from brpc_tpu.analysis.lockmodel import get_lock_model

_MARKERS = ("sampl", "record", "flight", "profil")


class SamplerNoLazyImportRule(Rule):
    name = "sampler-no-lazy-import"
    description = ("no import statement reachable from a sampler-"
                   "thread loop (first execution opens module files on "
                   "the sampler thread — fd churn mid-sample)")

    def finalize(self, ctx: Context) -> Iterable[Finding]:
        model = get_lock_model(ctx)
        roots: Set[str] = set()
        for creator, target_fkey, tname, _line in model.thread_roots:
            blob = " ".join((creator.cls or "", tname,
                             target_fkey.split("::")[-1])).lower()
            if any(m in blob for m in _MARKERS):
                roots.add(target_fkey)
        findings: List[Finding] = []
        reported: Set[tuple] = set()
        seen_roots: Set[str] = set()
        pending = sorted(roots)
        while pending:
            root = pending.pop(0)
            if root in seen_roots:
                continue
            seen_roots.add(root)
            for info, chain in model.same_module_closure(root):
                # marker-named callees in OTHER modules are sampler
                # code too (the tick crossing a module boundary):
                # recurse into their own same-module closures
                for callee, _, _ in info.resolved_calls:
                    target = model.funcs.get(callee)
                    if target is None or \
                            target.relpath == info.relpath or \
                            callee in seen_roots:
                        continue
                    leaf = callee.split("::")[-1].split(".")[-1].lower()
                    if any(m in leaf for m in _MARKERS):
                        pending.append(callee)
                for line, names in info.imports:
                    if (info.relpath, line) in reported:
                        continue
                    reported.add((info.relpath, line))
                    via = ("" if len(chain) == 1 else
                           " (reached via " + " -> ".join(
                               c.split("::")[-1] for c in chain) + ")")
                    findings.append(Finding(
                        self.name, info.relpath, line,
                        f"lazy import of '{names}' inside sampler-loop "
                        f"code '{info.qual}'{via} — the first execution "
                        "opens module files ON THE SAMPLER THREAD at "
                        "sample time; bind it at module load or in the "
                        "pre-thread-start bind step"))
        return findings
