"""postfork-reset: process-global singleton caches must survive fork.

Shard-group serving forks worker processes (rpc/shard_group.py); a
module that caches a process-global singleton — dispatcher, scheduler,
timer, socket map, pooled buffers — hands every forked child dead
threads, shared epoll fds and possibly-held locks unless it registers
a reset with ``butil.postfork``. The failure is the worst kind:
nothing crashes at fork time, the child just serves nothing (spawns
queue onto worker threads that only exist in the parent) or corrupts
the PARENT (EPOLL_CTL on the inherited epoll fd edits the parent's
interest list).

The rule recognizes the two singleton idioms this codebase uses and
requires the defining module to call ``postfork.register(...)`` (or a
function named ``register_postfork_reset``):

  1. the lazy-global accessor::

         _global = None
         def global_thing():
             global _global
             if _global is None:
                 _global = Thing()
             return _global

     i.e. a module-level function with a ``global NAME`` statement, an
     ``is None``/truthiness guard on NAME, and an assignment whose
     value constructs an object (a Call whose callee is CapitalizedName
     or x.CapitalizedAttr) — or calls a SAME-MODULE factory helper
     whose body constructs one (``_global = _new_dispatcher()`` where
     ``def _new_dispatcher(): return RingDispatcher() or
     EventDispatcher()``); the lane-selection indirection must not
     launder the singleton past the rule. Accessors that hand the
     instance to ``register_protocol`` are exempt: the protocol table
     is a fork-safe codec registry (pure data, no threads/fds), owned
     by protocol/registry.py.

  2. module-level instantiation of a resource-bearing class::

         pool = BlockPool(...)
         global_sampler = Sampler()

     flagged only when the constructed class's body (resolved across
     the analyzed file set) shows process-resource markers — it starts
     threads, opens files/sockets/selectors, or keeps reuse freelists.
     Plain data singletons (Adder(), Maxer(), compiled regexes) stay
     out of scope.

  3. the object-registry registrar::

         _modules = []
         def register_module(module):
             _modules.append(module)

     a module-level ``register*`` function appending its own parameter
     into a module-level list carries LIVE caller-owned objects across
     fork — a forked shard's fresh loops would drive the PARENT's
     registered engines/callbacks (fiber/worker_module.py is the
     canonical case: the child's workers would double-run the parent's
     serving engine against controllers the child does not own).
     ``register_protocol`` is exempt like the accessor case: the
     protocol table is fork-safe codec data. Registrars that copy or
     wrap the argument (``append((name, fn))``) stay out of scope —
     name-keyed provider tables are replace-on-reregister by
     convention here and fork-safe when their entries are.

A singleton that is genuinely fork-safe can waive with a reason::

    # graftlint: disable=postfork-reset -- <why the fork inherits this safely>
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, List, Optional, Set

from brpc_tpu.analysis.core import Context, Finding, Rule, SourceFile

# process-resource markers inside a class body: threads, fds, reuse
# caches — the things a forked child must not inherit silently
_RESOURCE_RE = re.compile(
    r"Thread\(|ThreadPoolExecutor|selectors\.|socketpair|os\.pipe|"
    r"\bopen\(|Popen\(|freelist|_freelists|\brecycle\b")


def _constructor_calls(value: ast.AST) -> List[str]:
    """Names of constructor-looking calls anywhere in ``value``:
    ``Thing()`` or ``mod.Thing()`` (leading-uppercase callee)."""
    out: List[str] = []
    for node in ast.walk(value):
        if not isinstance(node, ast.Call):
            continue
        name: Optional[str] = None
        if isinstance(node.func, ast.Name):
            name = node.func.id
        elif isinstance(node.func, ast.Attribute):
            name = node.func.attr
        if name and name[:1].isupper():
            out.append(name)
    return out


class PostforkResetRule(Rule):
    name = "postfork-reset"
    description = ("modules caching process-global singletons must "
                   "register a butil.postfork reset (forked shard "
                   "workers inherit dead threads / shared fds / held "
                   "locks otherwise)")

    # ----------------------------------------------------------- helpers
    def _has_registration(self, sf: SourceFile) -> bool:
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr == "register":
                base = f.value
                if isinstance(base, ast.Name) and "postfork" in base.id:
                    return True
                if isinstance(base, ast.Attribute) and \
                        "postfork" in base.attr:
                    return True
            if isinstance(f, ast.Name) and f.id == "register_postfork_reset":
                return True
        return False

    def _factory_constructs(self, sf: SourceFile, value: ast.AST) -> bool:
        """True when ``value`` calls a same-module factory helper whose
        body contains a constructor-looking call — the
        ``_global = _new_dispatcher()`` lane-selection idiom."""
        factories = {node.name: node for node in sf.tree.body
                     if isinstance(node, ast.FunctionDef)}
        for node in ast.walk(value):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Name):
                fac = factories.get(node.func.id)
                if fac is not None and _constructor_calls(fac):
                    return True
        return False

    def _lazy_singletons(self, sf: SourceFile) -> Iterable[ast.FunctionDef]:
        """Module-level functions matching the lazy-global accessor
        idiom (see module doc), excluding protocol registrars."""
        for node in sf.tree.body:
            if not isinstance(node, ast.FunctionDef):
                continue
            globals_: Set[str] = set()
            for sub in ast.walk(node):
                if isinstance(sub, ast.Global):
                    globals_.update(sub.names)
            if not globals_:
                continue
            guarded = False
            constructs = False
            registers_protocol = False
            for sub in ast.walk(node):
                if isinstance(sub, ast.Compare) and \
                        isinstance(sub.left, ast.Name) and \
                        sub.left.id in globals_ and \
                        any(isinstance(c, ast.Constant) and c.value is None
                            for c in sub.comparators):
                    guarded = True
                if isinstance(sub, ast.Assign):
                    tgt_hit = any(isinstance(t, ast.Name)
                                  and t.id in globals_
                                  for t in sub.targets)
                    if tgt_hit and (_constructor_calls(sub.value) or
                                    self._factory_constructs(sf, sub.value)):
                        constructs = True
                if isinstance(sub, ast.Call) and \
                        isinstance(sub.func, ast.Name) and \
                        sub.func.id == "register_protocol":
                    registers_protocol = True
            if guarded and constructs and not registers_protocol:
                yield node

    def _stateful_module_singletons(self, sf: SourceFile,
                                    ctx: Context) -> Iterable[ast.Assign]:
        """Top-level ``NAME = ResourceClass(...)`` assignments whose
        class body carries process-resource markers."""
        for node in sf.tree.body:
            if not isinstance(node, ast.Assign):
                continue
            for cls_name in _constructor_calls(node.value):
                hit = ctx.resolve_class(f"{sf.relpath}:{cls_name}") \
                    or ctx.resolve_class(cls_name)
                if hit is None:
                    continue
                cls_sf, cls_def = hit
                end = getattr(cls_def, "end_lineno", cls_def.lineno)
                body = "\n".join(
                    cls_sf.lines[cls_def.lineno - 1:end])
                if _RESOURCE_RE.search(body):
                    yield node
                    break

    def _registry_registrars(self, sf: SourceFile) \
            -> Iterable[ast.FunctionDef]:
        """Module-level ``register*`` functions appending their own
        parameter into a module-level list (idiom 3 in the module
        doc)."""
        module_lists: Set[str] = set()
        for node in sf.tree.body:
            targets: List[ast.expr] = []
            if isinstance(node, ast.Assign):
                targets = list(node.targets)
                value = node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets = [node.target]
                value = node.value
            else:
                continue
            if isinstance(value, ast.List):
                module_lists.update(t.id for t in targets
                                    if isinstance(t, ast.Name))
        if not module_lists:
            return
        for node in sf.tree.body:
            if not isinstance(node, ast.FunctionDef) or \
                    not node.name.startswith("register"):
                continue
            if node.name == "register_protocol":
                continue    # fork-safe codec table (module doc)
            params = {a.arg for a in node.args.args}
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) and \
                        isinstance(sub.func, ast.Attribute) and \
                        sub.func.attr == "append" and \
                        isinstance(sub.func.value, ast.Name) and \
                        sub.func.value.id in module_lists and \
                        sub.args and \
                        isinstance(sub.args[0], ast.Name) and \
                        sub.args[0].id in params:
                    yield node
                    break

    # -------------------------------------------------------------- check
    def check(self, sf: SourceFile, ctx: Context) -> Iterable[Finding]:
        if not sf.is_python or "/analysis/" in sf.relpath \
                or sf.relpath.endswith("butil/postfork.py"):
            return ()
        findings: List[Finding] = []
        registered = self._has_registration(sf)
        for fn in self._lazy_singletons(sf):
            if not registered:
                findings.append(Finding(
                    self.name, sf.relpath, fn.lineno,
                    f"'{fn.name}' caches a process-global singleton but "
                    "the module never registers a postfork reset "
                    "(butil.postfork.register) — forked shard workers "
                    "would inherit dead threads/shared fds"))
        for node in self._stateful_module_singletons(sf, ctx):
            if not registered:
                tgt = node.targets[0]
                nm = tgt.id if isinstance(tgt, ast.Name) else "?"
                findings.append(Finding(
                    self.name, sf.relpath, node.lineno,
                    f"module-level singleton '{nm}' holds process "
                    "resources (threads/fds/freelists) but the module "
                    "never registers a postfork reset "
                    "(butil.postfork.register)"))
        for fn in self._registry_registrars(sf):
            if not registered:
                findings.append(Finding(
                    self.name, sf.relpath, fn.lineno,
                    f"'{fn.name}' appends caller-owned objects into a "
                    "module-level registry but the module never "
                    "registers a postfork reset (butil.postfork."
                    "register) — a forked shard worker would run the "
                    "PARENT's registered objects"))
        return findings
