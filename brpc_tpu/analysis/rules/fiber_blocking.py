"""fiber-blocking: no carrier-pthread-blocking call reachable from a
fiber context.

Fibers here are coroutines multiplexed onto carrier pthreads
(brpc_tpu/fiber/scheduler.py); a synchronous blocking call inside one
stalls every other fiber sharing the carrier — the exact failure mode
bthread forbids with its "never call a blocking syscall from a
bthread" discipline. Fiber contexts are:

  * every ``async def`` in the package (fibers run coroutines);
  * ``parse`` / ``process`` / ``process_inline`` methods of Protocol
    subclasses (they run on the input path's fibers);
  * everything in transport/event_dispatcher.py and
    transport/ring_lane.py (the event loop / ring tick thread must
    never block on anything but its own poll);
  * the ring-lane completion entrypoints on Socket
    (``RING_COMPLETION_METHODS``): the batched tick drains its
    completion ring straight into them, so they are event-thread code
    wherever they live — the drain only queues bytes, retires writes
    and schedules fibers (ISSUE 15).

Context propagates through same-module synchronous calls (a helper
called from a fiber context is itself a fiber context). Awaited calls
are fine — ``await butex.wait()`` parks the FIBER, not the pthread;
that is the sanctioned equivalent. The worker-module boundary
(fiber/worker_module.py, where fibers intentionally hand work to
dedicated pthreads) and the fiber runtime's own pthread-side
internals (scheduler, butex pthread waiters, timer thread, device
poller, stack pool) are allowlisted: they ARE the blocking layer the
rest of the package must delegate to.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from brpc_tpu.analysis.core import Context, Finding, Rule, SourceFile

# modules that legitimately block: the fiber runtime's pthread side and
# the sanctioned worker boundary
ALLOWLIST = (
    "brpc_tpu/fiber/worker_module.py",
    "brpc_tpu/fiber/scheduler.py",
    "brpc_tpu/fiber/butex.py",
    "brpc_tpu/fiber/timer.py",
    "brpc_tpu/fiber/device_poller.py",
    "brpc_tpu/fiber/stacks.py",
    "brpc_tpu/fiber/execution_queue.py",
)

# event-loop modules where EVERY function is a fiber-adjacent context
CONTEXT_MODULES = ("brpc_tpu/transport/event_dispatcher.py",
                   "brpc_tpu/transport/ring_lane.py")

PROTOCOL_CONTEXT_METHODS = ("parse", "process", "process_inline")

# ring-lane completion entrypoints (ISSUE 15): the batched tick drains
# its completion ring straight into these Socket methods, so they run
# on the dispatcher thread even though they live outside the
# CONTEXT_MODULES — the drain must only queue bytes / retire writes /
# schedule fibers, mirroring the scan lane's deferred-timeout
# discipline (a blocking call here stalls EVERY fd in the batch)
RING_COMPLETION_METHODS = ("ring_input", "ring_settle_write",
                           "ring_collect_writes")

_SUBPROCESS_BLOCKING = ("run", "call", "check_call", "check_output",
                        "Popen", "getoutput", "getstatusoutput")


def _func_name(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


class _ModuleIndex:
    """Per-module: function defs, their blocking calls, their
    same-module callees, and which defs are fiber-context roots."""

    def __init__(self, sf: SourceFile, ctx: Context):
        self.sf = sf
        # key: "ClassName.func" or "func"
        self.defs: Dict[str, ast.AST] = {}
        self.roots: Set[str] = set()
        self.blocking: Dict[str, List[Tuple[int, str]]] = {}
        self.calls: Dict[str, Set[str]] = {}
        self._import_aliases(sf)
        self._collect(sf, ctx)

    def _import_aliases(self, sf: SourceFile) -> None:
        self.time_aliases: Set[str] = set()
        self.subprocess_aliases: Set[str] = set()
        self.socket_aliases: Set[str] = set()
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    alias = a.asname or a.name.split(".")[0]
                    if a.name == "time":
                        self.time_aliases.add(alias)
                    elif a.name == "subprocess":
                        self.subprocess_aliases.add(alias)
                    elif a.name == "socket":
                        self.socket_aliases.add(alias)
            elif isinstance(node, ast.ImportFrom):
                if node.module == "time":
                    for a in node.names:
                        if a.name == "sleep":
                            self.time_aliases.add(
                                f"\x00direct:{a.asname or a.name}")
                elif node.module == "subprocess":
                    for a in node.names:
                        if a.name in _SUBPROCESS_BLOCKING:
                            self.subprocess_aliases.add(
                                f"\x00direct:{a.asname or a.name}")

    # ------------------------------------------------------- collection
    def _collect(self, sf: SourceFile, ctx: Context) -> None:
        protocol_classes = _protocol_class_names(ctx)
        module_is_context = sf.relpath.endswith(CONTEXT_MODULES)

        class V(ast.NodeVisitor):
            def __init__(v):
                v.stack: List[str] = []
                v.class_stack: List[ast.ClassDef] = []

            def _enter(v, node, is_async: bool):
                cls = v.class_stack[-1].name if v.class_stack else None
                key = f"{cls}.{node.name}" if cls else node.name
                self.defs[key] = node
                if is_async or module_is_context:
                    self.roots.add(key)
                elif (cls is not None
                      and node.name in PROTOCOL_CONTEXT_METHODS
                      and cls in protocol_classes):
                    self.roots.add(key)
                elif node.name in RING_COMPLETION_METHODS:
                    self.roots.add(key)
                v.stack.append(key)
                for child in node.body:
                    v.visit(child)
                v.stack.pop()

            def visit_FunctionDef(v, node):
                v._enter(node, False)

            def visit_AsyncFunctionDef(v, node):
                v._enter(node, True)

            def visit_ClassDef(v, node):
                v.class_stack.append(node)
                for child in node.body:
                    v.visit(child)
                v.class_stack.pop()

        V().visit(sf.tree)
        # second pass, against the COMPLETE def table: helpers are
        # routinely defined below their callers, and resolving calls
        # during collection would silently drop every forward edge
        for key, node in self.defs.items():
            _FuncScan(self, key).scan(node)


class _FuncScan:
    """One function body: record blocking calls (not under Await, not
    inside a nested def) and same-module callee names."""

    def __init__(self, idx: _ModuleIndex, key: str):
        self.idx = idx
        self.key = key
        self.local_sockets: Set[str] = set()
        self.local_events: Set[str] = set()

    def scan(self, func: ast.AST) -> None:
        idx = self.idx
        idx.blocking.setdefault(self.key, [])
        idx.calls.setdefault(self.key, set())
        awaited: Set[int] = set()
        for node in ast.walk(func):
            if isinstance(node, ast.Await) and isinstance(node.value,
                                                          ast.Call):
                awaited.add(id(node.value))
        skip: Set[int] = set()
        for node in ast.walk(func):
            if node is not func and isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for sub in ast.walk(node):
                    skip.add(id(sub))
        for node in ast.walk(func):
            if id(node) in skip:
                continue
            if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call):
                self._track_assign(node)
            if not isinstance(node, ast.Call) or id(node) in awaited:
                continue
            hit = self._blocking_reason(node)
            if hit:
                idx.blocking[self.key].append((node.lineno, hit))
                continue
            callee = self._same_module_callee(node)
            if callee:
                idx.calls[self.key].add(callee)

    def _track_assign(self, node: ast.Assign) -> None:
        call = node.value
        fn = call.func
        mod = fn.value.id if (isinstance(fn, ast.Attribute) and
                              isinstance(fn.value, ast.Name)) else None
        for tgt in node.targets:
            if not isinstance(tgt, ast.Name):
                continue
            if (mod in self.idx.socket_aliases
                    and isinstance(fn, ast.Attribute)
                    and fn.attr == "socket"):
                self.local_sockets.add(tgt.id)
            if (isinstance(fn, ast.Attribute) and mod == "threading"
                    and fn.attr in ("Event", "Condition")):
                self.local_events.add(tgt.id)

    def _blocking_reason(self, call: ast.Call) -> Optional[str]:
        idx = self.idx
        fn = call.func
        if isinstance(fn, ast.Name):
            if f"\x00direct:{fn.id}" in idx.time_aliases:
                return "time.sleep() blocks the carrier pthread"
            if f"\x00direct:{fn.id}" in idx.subprocess_aliases:
                return f"subprocess.{fn.id}() blocks the carrier pthread"
            return None
        if not isinstance(fn, ast.Attribute):
            return None
        base = fn.value
        base_name = base.id if isinstance(base, ast.Name) else None
        if base_name in idx.time_aliases and fn.attr == "sleep":
            return "time.sleep() blocks the carrier pthread"
        if (base_name in idx.subprocess_aliases
                and fn.attr in _SUBPROCESS_BLOCKING):
            return f"subprocess.{fn.attr}() blocks the carrier pthread"
        if (base_name in idx.socket_aliases
                and fn.attr == "create_connection"):
            return "socket.create_connection() blocks the carrier pthread"
        if (base_name in self.local_sockets
                and fn.attr in ("connect", "accept", "recv", "recvfrom",
                                "sendall", "makefile")):
            return (f"blocking socket.{fn.attr}() on a socket created "
                    "in this fiber context")
        if fn.attr == "acquire" and _lockish(fn.value):
            if not _nonblocking_acquire(call):
                return ("Lock.acquire() parks the carrier pthread — use "
                        "fiber.sync/butex primitives (or "
                        "acquire(blocking=False))")
        if fn.attr == "wait" and base_name in self.local_events:
            return ("threading.Event/Condition.wait() blocks the carrier "
                    "pthread — use fiber.sync.FiberEvent")
        return None

    def _same_module_callee(self, call: ast.Call) -> Optional[str]:
        fn = call.func
        if isinstance(fn, ast.Name) and fn.id in self.idx.defs:
            return fn.id
        if (isinstance(fn, ast.Attribute)
                and isinstance(fn.value, ast.Name)
                and fn.value.id == "self"):
            cls = self.key.split(".")[0] if "." in self.key else None
            if cls and f"{cls}.{fn.attr}" in self.idx.defs:
                return f"{cls}.{fn.attr}"
        return None


def _lockish(node: ast.AST) -> bool:
    name = None
    if isinstance(node, ast.Attribute):
        name = node.attr
    elif isinstance(node, ast.Name):
        name = node.id
    return name is not None and "lock" in name.lower()


def _nonblocking_acquire(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "blocking" and isinstance(kw.value, ast.Constant) \
                and kw.value.value is False:
            return True
    if call.args and isinstance(call.args[0], ast.Constant) \
            and call.args[0].value is False:
        return True
    return False


def _protocol_class_names(ctx: Context) -> Set[str]:
    """Names of classes anywhere in the file set whose MRO reaches the
    registry's Protocol base."""
    cached = getattr(ctx, "_fiber_protocol_classes", None)
    if cached is not None:
        return cached
    out: Set[str] = set()
    for key, (sf, node) in ctx.classes.items():
        if ":" not in key:
            continue
        for _, c in ctx.mro_class_defs(sf, node):
            if c.name == "Protocol":
                out.add(node.name)
                break
    ctx._fiber_protocol_classes = out
    return out


class FiberBlockingRule(Rule):
    name = "fiber-blocking"
    description = ("no pthread-blocking call (time.sleep, subprocess, "
                   "blocking socket ops, Lock.acquire, Event.wait) "
                   "reachable from a fiber/event-dispatcher/protocol-"
                   "handler context")

    def check(self, sf: SourceFile, ctx: Context) -> Iterable[Finding]:
        if not sf.is_python:
            return ()
        if sf.relpath.endswith(ALLOWLIST) or "/analysis/" in sf.relpath:
            return ()
        idx = _ModuleIndex(sf, ctx)
        findings: List[Finding] = []
        reported: Set[Tuple[int, str]] = set()
        for root in sorted(idx.roots):
            # reach the same-module closure of each fiber context
            stack, seen = [(root, (root,))], set()
            while stack:
                key, chain = stack.pop()
                if key in seen:
                    continue
                seen.add(key)
                for line, why in idx.blocking.get(key, ()):
                    if (line, why) in reported:
                        continue
                    reported.add((line, why))
                    via = ("" if len(chain) == 1 else
                           " (reached via " + " -> ".join(chain) + ")")
                    findings.append(Finding(
                        self.name, sf.relpath, line,
                        f"{why} in fiber context '{key}'{via}"))
                for callee in idx.calls.get(key, ()):
                    stack.append((callee, chain + (callee,)))
        return findings
