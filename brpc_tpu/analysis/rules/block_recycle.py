"""block-recycle: a view into pooled blocks must not outlive the
buffer's recycle point.

``first_host_view()`` / ``BlockRef.memoryview()`` hand out windows into
POOLED block storage (butil/iobuf.py BlockPool): the bytes stay valid
only while the source buffer still references the block. The consuming
ops — ``pop_front`` / ``cut`` / ``cut_all`` / ``cut_into`` / ``clear``
— drop refs, and once the last ref is gone the block's buffer returns
to the freelist, where the next acquire (or the debug poisoner) rewrites
it under the still-held view. Reading such a view is silent corruption
in production and 0xDD garbage under ``BRPC_TPU_IOBUF_DEBUG=1``; the
scan lanes' discipline is slice-then-pop (turbo_scan copies every
payload out of the window BEFORE ``portal.pop_front``).

Detection is a per-function may-analysis mirroring iobuf-aliasing's
skeleton: a name bound from a view-producing method (or a subscript of
a tracked view — slicing a memoryview is still a view) is tied to its
source expression; a consuming call on that source marks the view
STALE; any later load of a stale name is a finding until the name is
rebound. Disjoint if/else branches don't poison each other (a consume
on either poisons the join), and loop bodies are scanned twice so a
late-iteration consume reaches the next pass's head. The buffer
implementation itself (butil/iobuf.py) owns its internals and is
excluded, like /analysis/ everywhere.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Set, Tuple

from brpc_tpu.analysis.core import Context, Finding, Rule, SourceFile

# methods whose result is a live window into pooled block storage
VIEW_METHODS = ("first_host_view", "memoryview")
# ops that drop block refs from the source buffer (the recycle point)
CONSUMERS = ("pop_front", "cut", "cut_all", "cut_into", "clear")


def _expr_key(node: ast.AST) -> str:
    """Stable key for a source-buffer expression (Name / dotted attr)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return f"{_expr_key(node.value)}.{node.attr}"
    return ast.dump(node)


class BlockRecycleRule(Rule):
    name = "block-recycle"
    description = ("no use of a memoryview/BlockRef window into pooled "
                   "blocks after the source buffer's recycle point "
                   "(pop_front/cut/clear)")

    def check(self, sf: SourceFile, ctx: Context) -> Iterable[Finding]:
        if not sf.is_python or "/analysis/" in sf.relpath \
                or sf.relpath.endswith("butil/iobuf.py"):
            return ()
        findings: List[Finding] = []
        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                findings.extend(self._scan_function(sf, node))
        return findings

    def _scan_function(self, sf: SourceFile, func: ast.AST) -> List[Finding]:
        findings: List[Finding] = []
        seen: Set[Tuple[int, str]] = set()

        def emit(lineno: int, name: str, src: str, via: str) -> None:
            key = (lineno, name)
            if key in seen:            # loop bodies are scanned twice
                return
            seen.add(key)
            findings.append(Finding(
                self.name, sf.relpath, lineno,
                f"'{name}' is a view into '{src}''s pooled blocks and "
                f"is used after '{src}.{via}()' — the blocks may "
                "already be recycled (poisoned under "
                "BRPC_TPU_IOBUF_DEBUG); copy the bytes out before the "
                "cut/pop"))

        # views: name -> source key; stale: name -> consuming method
        def apply_expr(node: ast.AST, views: Dict[str, str],
                       stale: Dict[str, str]) -> None:
            events = []   # (lineno, col, kind, payload)
            for sub in ast.walk(node):
                if isinstance(sub, ast.Assign):
                    for tgt in sub.targets:
                        if not isinstance(tgt, ast.Name):
                            continue
                        v = sub.value
                        if isinstance(v, ast.Call) and isinstance(
                                v.func, ast.Attribute) and \
                                v.func.attr in VIEW_METHODS:
                            events.append((sub.lineno, sub.col_offset,
                                           "bind",
                                           (tgt.id,
                                            _expr_key(v.func.value))))
                        elif isinstance(v, ast.Subscript) and isinstance(
                                v.value, ast.Name):
                            # a slice of a tracked view is still a view
                            events.append((sub.lineno, sub.col_offset,
                                           "derive",
                                           (tgt.id, v.value.id)))
                        else:
                            events.append((sub.lineno, sub.col_offset,
                                           "rebind", (tgt.id, "")))
                elif isinstance(sub, ast.Delete):
                    for tgt in sub.targets:
                        if isinstance(tgt, ast.Name):
                            events.append((sub.lineno, sub.col_offset,
                                           "rebind", (tgt.id, "")))
                elif isinstance(sub, ast.Call) and isinstance(
                        sub.func, ast.Attribute) and \
                        sub.func.attr in CONSUMERS:
                    events.append((sub.lineno, sub.col_offset, "consume",
                                   (_expr_key(sub.func.value),
                                    sub.func.attr)))
                elif isinstance(sub, ast.Name) and isinstance(
                        sub.ctx, ast.Load):
                    events.append((sub.lineno, sub.col_offset, "load",
                                   (sub.id, "")))
            events.sort(key=lambda e: (e[0], e[1]))
            for lineno, _col, kind, payload in events:
                if kind == "bind":
                    name, src = payload
                    views[name] = src
                    stale.pop(name, None)
                elif kind == "derive":
                    name, parent = payload
                    if parent in views:
                        views[name] = views[parent]
                        if parent in stale:
                            stale[name] = stale[parent]
                        else:
                            stale.pop(name, None)
                    else:
                        views.pop(name, None)
                        stale.pop(name, None)
                elif kind == "rebind":
                    views.pop(payload[0], None)
                    stale.pop(payload[0], None)
                elif kind == "consume":
                    src, via = payload
                    for name, vsrc in views.items():
                        if vsrc == src:
                            stale[name] = via
                elif kind == "load":
                    name = payload[0]
                    if name in stale:
                        emit(lineno, name, views.get(name, "?"),
                             stale[name])

        def scan_stmts(stmts, views: Dict[str, str],
                       stale: Dict[str, str]) -> None:
            for st in stmts:
                if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef)):
                    continue   # nested defs scan as their own functions
                if isinstance(st, ast.If):
                    apply_expr(st.test, views, stale)
                    vb, sb = dict(views), dict(stale)
                    ve, se = dict(views), dict(stale)
                    scan_stmts(st.body, vb, sb)
                    scan_stmts(st.orelse, ve, se)
                    views.clear(); views.update(ve); views.update(vb)
                    stale.clear(); stale.update(se); stale.update(sb)
                elif isinstance(st, (ast.For, ast.AsyncFor, ast.While)):
                    apply_expr(st.iter if isinstance(
                        st, (ast.For, ast.AsyncFor)) else st.test,
                        views, stale)
                    v, s = dict(views), dict(stale)
                    scan_stmts(st.body, v, s)      # two-pass unroll: a
                    scan_stmts(st.body, v, s)      # late consume reaches
                    scan_stmts(st.orelse, v, s)    # the next pass's head
                    views.update(v)
                    stale.update(s)
                elif isinstance(st, (ast.With, ast.AsyncWith)):
                    for item in st.items:
                        apply_expr(item.context_expr, views, stale)
                    scan_stmts(st.body, views, stale)
                elif isinstance(st, ast.Try):
                    scan_stmts(st.body, views, stale)
                    for handler in st.handlers:
                        v, s = dict(views), dict(stale)
                        scan_stmts(handler.body, v, s)
                        views.update(v)
                        stale.update(s)
                    scan_stmts(st.orelse, views, stale)
                    scan_stmts(st.finalbody, views, stale)
                else:
                    apply_expr(st, views, stale)

        scan_stmts(func.body, {}, {})
        return findings
