"""registry-complete: every registered protocol is a complete citizen.

A Protocol registered into the global table is probed against ALL
inbound bytes (the InputMessenger tries each in turn), so a registered
class missing its contract surfaces as a runtime NotImplementedError
on the first foreign frame — the worst possible place. The rule
resolves every ``register_protocol(X)`` call site and checks, over the
class's MRO across the analyzed file set:

  * a concrete ``parse`` (not the raising base stub);
  * a concrete ``process`` or ``process_inline`` override (either
    dispatch surface satisfies the input path);
  * a client-side encoding surface — ``serialize_request`` /
    ``pack_request`` on the class, or a module-level pack/serialize
    function in any MRO module (most protocols here pack at module
    scope);
  * an error vocabulary: the MRO modules (or the brpc_tpu modules
    they import) must reference an errno mapping — ``errno_codes``,
    ``error_code``, a ``STATUS_*`` table, or an ``*Error`` exception
    class — so failures map to SOMETHING a peer can interpret.

The same discipline covers the concurrency-limiter spec parser
(``new_limiter``): a spec string names a limiter class the Server
drives on its admission hot path, so every class the parser can
construct must implement the full ConcurrencyLimiter contract —
concrete ``on_requested``, ``on_responded`` and ``max_concurrency``
(a raising stub would turn ``max_concurrency="auto"`` into a
first-request crash).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from brpc_tpu.analysis.core import Context, Finding, Rule, SourceFile

_ERRNO_RE = re.compile(
    r"errno_codes|error_code|STATUS_[A-Z]|[A-Z]\w*Error\b")
_PACKISH_RE = re.compile(
    r"def\s+\w*(pack|serialize|encode|reply|response)\w*\s*\(")


def _is_raise_stub(node: ast.AST) -> bool:
    """A def whose body (docstring aside) is just ``raise
    NotImplementedError`` — an abstract stub, not an implementation."""
    body = list(getattr(node, "body", ()))
    if body and isinstance(body[0], ast.Expr) \
            and isinstance(body[0].value, ast.Constant) \
            and isinstance(body[0].value.value, str):
        body = body[1:]
    if len(body) != 1 or not isinstance(body[0], ast.Raise):
        return False
    exc = body[0].exc
    if isinstance(exc, ast.Call):
        exc = exc.func
    return isinstance(exc, ast.Name) and exc.id == "NotImplementedError"


class RegistryCompleteRule(Rule):
    name = "registry-complete"
    description = ("every register_protocol()ed class must expose "
                   "parse + process(+_inline) + a pack/serialize "
                   "surface + an errno mapping")

    # the ConcurrencyLimiter contract the Server's admission gate
    # calls on every request (rpc/concurrency_limiter.py)
    LIMITER_CONTRACT = ("on_requested", "on_responded", "max_concurrency")

    def check(self, sf: SourceFile, ctx: Context) -> Iterable[Finding]:
        if not sf.is_python or "/analysis/" in sf.relpath:
            return ()
        findings: List[Finding] = []
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.FunctionDef) \
                    and node.name == "new_limiter":
                findings.extend(self._check_limiter_parser(sf, node, ctx))
                continue
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "register_protocol"
                    and node.args):
                continue
            cls = self._resolve_class(sf, node.args[0])
            if cls is None:
                continue
            hit = ctx.resolve_class(f"{sf.relpath}:{cls}") \
                or ctx.resolve_class(cls)
            if hit is None:
                continue
            findings.extend(self._check_class(sf, node.lineno, hit, ctx))
        return findings

    # ------------------------------------------- limiter spec parser
    def _check_limiter_parser(self, sf: SourceFile, fn: ast.FunctionDef,
                              ctx: Context) -> Iterable[Finding]:
        """Every class the spec parser can construct must be a complete
        ConcurrencyLimiter: its contract methods run on the server's
        per-request admission path, so an inherited raising stub is a
        crash wired to a config string."""
        findings: List[Finding] = []
        seen: Set[str] = set()
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)):
                continue
            name = node.func.id
            if name in seen:
                continue
            seen.add(name)
            hit = ctx.resolve_class(f"{sf.relpath}:{name}") \
                or ctx.resolve_class(name)
            if hit is None:
                continue   # int()/float()/errors — not a class here
            hit_sf, hit_cls = hit
            methods: Dict[str, Tuple[str, ast.AST]] = {}
            for m_sf, m_cls in ctx.mro_class_defs(hit_sf, hit_cls):
                for item in m_cls.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)) \
                            and item.name not in methods:
                        methods[item.name] = (m_cls.name, item)
            for want in self.LIMITER_CONTRACT:
                owner = methods.get(want)
                if owner is not None and not _is_raise_stub(owner[1]):
                    continue
                findings.append(Finding(
                    self.name, sf.relpath, node.lineno,
                    f"limiter spec parser constructs '{name}' with no "
                    f"concrete {want}() — the Server's admission gate "
                    "calls the full ConcurrencyLimiter contract on "
                    "every request"))
        return findings

    def _resolve_class(self, sf: SourceFile,
                       arg: ast.AST) -> Optional[str]:
        """The class behind register_protocol's argument: a direct
        Class() call, or a name assigned from one anywhere in the
        module (the `_instance = Proto()` idiom)."""
        if isinstance(arg, ast.Call) and isinstance(arg.func, ast.Name):
            return arg.func.id
        if not isinstance(arg, ast.Name):
            return None
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call) \
                    and isinstance(node.value.func, ast.Name):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name) and tgt.id == arg.id:
                        return node.value.func.id
            elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                continue
        return None

    def _check_class(self, sf: SourceFile, line: int,
                     hit: Tuple[SourceFile, ast.ClassDef],
                     ctx: Context) -> Iterable[Finding]:
        cls_sf, cls = hit
        mro = ctx.mro_class_defs(cls_sf, cls)
        findings: List[Finding] = []
        methods: Dict[str, Tuple[str, ast.AST]] = {}
        for m_sf, m_cls in mro:
            for item in m_cls.body:
                if isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) \
                        and item.name not in methods:
                    methods[item.name] = (m_cls.name, item)

        def concrete(name: str) -> bool:
            owner = methods.get(name)
            if owner is None:
                return False
            owner_cls, node = owner
            if owner_cls == "Protocol":
                # the base's parse/process raise NotImplementedError;
                # its process_inline returning False is NOT a dispatch
                # surface on its own
                return name not in ("parse", "process", "process_inline")
            return True

        if not concrete("parse"):
            findings.append(Finding(
                self.name, sf.relpath, line,
                f"registered protocol '{cls.name}' has no concrete "
                "parse() — the InputMessenger probes every registered "
                "protocol against inbound bytes"))
        if not (concrete("process") or concrete("process_inline")):
            findings.append(Finding(
                self.name, sf.relpath, line,
                f"registered protocol '{cls.name}' has no concrete "
                "process()/process_inline() — parsed messages would "
                "raise on dispatch"))
        mro_files = {m_sf for m_sf, _ in mro}
        if not (concrete("serialize_request") or concrete("pack_request")
                or any(_PACKISH_RE.search(f.text) for f in mro_files)):
            findings.append(Finding(
                self.name, sf.relpath, line,
                f"registered protocol '{cls.name}' exposes no pack/"
                "serialize surface (class hook or module-level "
                "pack/serialize/encode function)"))
        if not self._has_errno_vocabulary(mro_files, ctx):
            findings.append(Finding(
                self.name, sf.relpath, line,
                f"registered protocol '{cls.name}' maps errors to "
                "nothing: no errno_codes/error_code/STATUS_*/*Error "
                "reference in its modules or their imports"))
        return findings

    def _has_errno_vocabulary(self, mro_files: Set[SourceFile],
                              ctx: Context) -> bool:
        seen: Set[str] = set()
        queue = list(mro_files)
        hops = {f.relpath: 0 for f in queue}
        while queue:
            f = queue.pop(0)
            if f.relpath in seen:
                continue
            seen.add(f.relpath)
            if _ERRNO_RE.search(f.text):
                return True
            if hops.get(f.relpath, 0) >= 2:
                continue
            for node in ast.walk(f.tree):
                mod = None
                if isinstance(node, ast.ImportFrom) and node.module:
                    mod = node.module
                elif isinstance(node, ast.Import):
                    for a in node.names:
                        if a.name.startswith("brpc_tpu"):
                            mod = a.name
                if not mod or not mod.startswith("brpc_tpu"):
                    continue
                rel = mod.replace(".", "/") + ".py"
                nxt = ctx.by_relpath.get(rel)
                if nxt is not None and nxt.relpath not in seen:
                    hops[nxt.relpath] = hops.get(f.relpath, 0) + 1
                    queue.append(nxt)
        return False
