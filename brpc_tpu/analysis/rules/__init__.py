"""graftlint rule registry."""

from __future__ import annotations

from typing import List

from brpc_tpu.analysis.core import Rule


def default_rules() -> List[Rule]:
    from brpc_tpu.analysis.rules.block_recycle import BlockRecycleRule
    from brpc_tpu.analysis.rules.event_wait import EventWaitNotSleepRule
    from brpc_tpu.analysis.rules.fiber_blocking import FiberBlockingRule
    from brpc_tpu.analysis.rules.guarded_by import GuardedByRule
    from brpc_tpu.analysis.rules.iobuf_aliasing import IOBufAliasingRule
    from brpc_tpu.analysis.rules.judge_defer import JudgeDeferRule
    from brpc_tpu.analysis.rules.lock_graph import (
        BlockingUnderLockRule, CallbackUnderLockRule, LockCycleRule,
    )
    from brpc_tpu.analysis.rules.memoryview_release import (
        MemoryviewReleaseRule,
    )
    from brpc_tpu.analysis.rules.postfork_reset import PostforkResetRule
    from brpc_tpu.analysis.rules.registry_complete import (
        RegistryCompleteRule,
    )
    from brpc_tpu.analysis.rules.sampler_import import (
        SamplerNoLazyImportRule,
    )
    from brpc_tpu.analysis.rules.span_finish import SpanFinishRule
    return [BlockRecycleRule(), BlockingUnderLockRule(),
            CallbackUnderLockRule(), EventWaitNotSleepRule(),
            FiberBlockingRule(), GuardedByRule(),
            IOBufAliasingRule(), JudgeDeferRule(),
            LockCycleRule(), MemoryviewReleaseRule(),
            PostforkResetRule(), RegistryCompleteRule(),
            SamplerNoLazyImportRule(), SpanFinishRule()]
