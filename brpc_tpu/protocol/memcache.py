"""Memcached binary protocol client (src/brpc/memcache.{h,cpp} — the
890-line MemcacheRequest/Response pair — and
policy/memcache_binary_protocol.cpp). Client-side only, like the
reference.

Binary framing: 24-byte header
  magic:u8 opcode:u8 key_len:u16 extras_len:u8 data_type:u8
  vbucket_or_status:u16 total_body:u32 opaque:u32 cas:u64
Responses arrive strictly in request order per connection (memcached
serializes per-conn), so FIFO batch matching applies — the opaque field
is still checked as a desync tripwire."""

from __future__ import annotations

import itertools
import struct
import threading
from typing import List, NamedTuple, Optional, Tuple

from brpc_tpu.butil.endpoint import EndPoint
from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.fiber import TaskControl
from brpc_tpu.protocol.registry import (
    PARSE_NOT_ENOUGH_DATA, PARSE_OK, PARSE_TRY_OTHERS, Protocol,
    register_protocol,
)
from brpc_tpu.transport.pipelined import PipelinedClient

_HDR = struct.Struct(">BBHBBHIIQ")
HEADER_SIZE = 24
MAGIC_REQUEST = 0x80
MAGIC_RESPONSE = 0x81

# opcodes (protocol_binary.h of upstream memcached)
OP_GET = 0x00
OP_SET = 0x01
OP_ADD = 0x02
OP_REPLACE = 0x03
OP_DELETE = 0x04
OP_INCREMENT = 0x05
OP_DECREMENT = 0x06
OP_FLUSH = 0x08
OP_NOOP = 0x0A
OP_VERSION = 0x0B
OP_APPEND = 0x0E
OP_PREPEND = 0x0F
OP_TOUCH = 0x1C
OP_SASL_LIST_MECHS = 0x20
OP_SASL_AUTH = 0x21
OP_SASL_STEP = 0x22

# status codes
STATUS_OK = 0x0000
STATUS_KEY_NOT_FOUND = 0x0001
STATUS_KEY_EXISTS = 0x0002
STATUS_VALUE_TOO_LARGE = 0x0003
STATUS_INVALID_ARGUMENTS = 0x0004
STATUS_ITEM_NOT_STORED = 0x0005
STATUS_NON_NUMERIC = 0x0006
STATUS_AUTH_ERROR = 0x0020
STATUS_AUTH_CONTINUE = 0x0021

_MAX_BODY = 64 << 20


class MemcacheError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(f"[0x{status:04x}] {message}")
        self.status = status
        self.message = message


class Response(NamedTuple):
    opcode: int
    status: int
    opaque: int
    cas: int
    extras: bytes
    key: bytes
    value: bytes


class GetResult(NamedTuple):
    value: bytes
    flags: int
    cas: int


def pack_request(opcode: int, key: bytes = b"", value: bytes = b"",
                 extras: bytes = b"", opaque: int = 0, cas: int = 0) -> bytes:
    total = len(extras) + len(key) + len(value)
    return _HDR.pack(MAGIC_REQUEST, opcode, len(key), len(extras), 0, 0,
                     total, opaque, cas) + extras + key + value


def parse_response(data: bytes, pos: int) -> Optional[Tuple[Response, int]]:
    """One complete response frame at ``pos`` or None if incomplete.
    Raises ValueError on a frame that can never be a binary response."""
    if len(data) - pos < HEADER_SIZE:
        return None
    (magic, opcode, key_len, extras_len, _dtype, status, total, opaque,
     cas) = _HDR.unpack_from(data, pos)
    if magic != MAGIC_RESPONSE:
        raise ValueError(f"bad response magic 0x{magic:02x}")
    if total > _MAX_BODY or extras_len + key_len > total:
        raise ValueError("bad body lengths")
    if len(data) - pos < HEADER_SIZE + total:
        return None
    body = pos + HEADER_SIZE
    extras = data[body:body + extras_len]
    key = data[body + extras_len:body + extras_len + key_len]
    value = data[body + extras_len + key_len:body + total]
    return (Response(opcode, status, opaque, cas, extras, key, value),
            body + total)


class MemcacheProtocol(Protocol):
    """Client-side parser: binary responses on sockets owned by a
    MemcacheClient. Never claims server-side bytes."""

    name = "memcache"

    def parse(self, portal, socket) -> Tuple[str, object]:
        client = socket.user_data.get("memcache_client")
        if client is None:
            return PARSE_TRY_OTHERS, None
        # parse every complete frame off one flattened peek (a pipelined
        # multi-get burst would otherwise cost O(N^2) in re-peeks)
        data = portal.peek_bytes(portal.size)
        frames: List[Response] = []
        pos = 0
        while pos < len(data):
            try:
                got = parse_response(data, pos)
            except ValueError as e:
                socket.set_failed(
                    ConnectionError(f"corrupt memcache stream: {e}"))
                return PARSE_NOT_ENOUGH_DATA, None
            if got is None:
                break
            resp, pos = got
            frames.append(resp)
        if not frames:
            return PARSE_NOT_ENOUGH_DATA, None
        portal.pop_front(pos)
        return PARSE_OK, frames

    def process_inline(self, msgs: List[Response], socket) -> bool:
        client = socket.user_data.get("memcache_client")
        if client is not None:
            for msg in msgs:
                client._on_reply(socket, msg)
        return True

    def process(self, msg, socket):
        raise AssertionError("memcache responses are processed inline")


class MemcacheClient(PipelinedClient):
    """get/set/add/replace/append/prepend/delete/incr/decr/touch/version/
    flush_all over one pipelined connection (MemcacheRequest's batched
    api maps to ``pipeline_get``)."""

    user_data_key = "memcache_client"

    def __init__(self, address: str | EndPoint, timeout_s: float = 5.0,
                 control: Optional[TaskControl] = None,
                 username: Optional[str] = None,
                 password: Optional[str] = None):
        """username/password enable SASL PLAIN authentication on every
        fresh connection (the couchbase_authenticator.cpp role: the
        reference authenticates memcache/couchbase connections with a
        SASL PLAIN token before user commands)."""
        super().__init__(address, ensure_registered(), timeout_s=timeout_s,
                         control=control)
        if password is not None and username is None:
            # a silently-dropped password would leave the connection
            # unauthenticated with no hint why commands fail
            raise ValueError("memcache SASL: password given without "
                             "username")
        self._opaque = itertools.count(1)
        self._username = username
        self._password = password or ""
        # thread-local: _hello_commands and _check_hello_reply both run
        # on the connecting thread inside one _get_socket call, while a
        # concurrent connect on another thread generates its own opaque
        # — instance state here would let one connection's check compare
        # against the other's opaque
        self._sasl_expect = threading.local()

    # ----------------------------------------------------------- sasl auth
    def _hello_commands(self):
        if self._username is None:
            return []
        token = b"\x00" + self._username.encode() + \
            b"\x00" + self._password.encode()
        self._sasl_expect.opaque = next(self._opaque)
        return [pack_request(OP_SASL_AUTH, b"PLAIN", token,
                             opaque=self._sasl_expect.opaque)]

    def _check_hello_reply(self, reply) -> None:
        # strict: the hello reply must BE the SASL reply (same desync
        # tripwire as _call) — a stray frame here must not be mistaken
        # for a successful authentication
        expected = getattr(self._sasl_expect, "opaque", None)
        if reply.opcode != OP_SASL_AUTH or reply.opaque != expected:
            raise MemcacheError(-1, "sasl reply desync "
                                f"(opcode 0x{reply.opcode:02x})")
        if reply.status != STATUS_OK:
            raise MemcacheError(
                reply.status,
                reply.value.decode("latin1", "replace") or "auth failure")

    # ------------------------------------------------------------ helpers
    def _call(self, opcode: int, key: bytes = b"", value: bytes = b"",
              extras: bytes = b"", cas: int = 0) -> Response:
        opaque = next(self._opaque)
        wire = pack_request(opcode, key, value, extras, opaque, cas)
        batch = self._start(wire, 1)
        resp: Response = self._wait(batch, f"memcache op 0x{opcode:02x}")[0]
        return self._check_reply(resp, opaque, opcode, batch)

    async def _call_async(self, opcode: int, key: bytes = b"",
                          value: bytes = b"", extras: bytes = b"",
                          cas: int = 0) -> Response:
        """Fiber-friendly _call: awaits the reply instead of parking the
        worker thread (same contract as redis execute_async / thrift
        call_async)."""
        opaque = next(self._opaque)
        wire = pack_request(opcode, key, value, extras, opaque, cas)
        batch = self._start(wire, 1)
        resp: Response = (await self._wait_async(
            batch, f"memcache op 0x{opcode:02x}"))[0]
        return self._check_reply(resp, opaque, opcode, batch)

    def _check_reply(self, resp: Response, opaque: int, opcode: int,
                     batch) -> Response:
        if resp.opaque != opaque or resp.opcode != opcode:
            # FIFO desync: fail the connection, nothing after this can match
            if batch.socket is not None:
                batch.socket.set_failed(
                    ConnectionError("memcache reply desync"))
            raise MemcacheError(-1, "reply desync (opaque mismatch)")
        return resp

    @staticmethod
    def _key(key) -> bytes:
        return key.encode() if isinstance(key, str) else bytes(key)

    @staticmethod
    def _val(value) -> bytes:
        return value.encode() if isinstance(value, str) else bytes(value)

    @staticmethod
    def _raise(resp: Response):
        raise MemcacheError(resp.status,
                            resp.value.decode("latin1", "replace")
                            or f"status 0x{resp.status:04x}")

    # ---------------------------------------------------------------- api
    def get(self, key) -> Optional[GetResult]:
        return self._get_result(self._call(OP_GET, self._key(key)))

    async def get_async(self, key) -> Optional[GetResult]:
        return self._get_result(await self._call_async(OP_GET,
                                                       self._key(key)))

    def _get_result(self, resp: Response) -> Optional[GetResult]:
        if resp.status == STATUS_KEY_NOT_FOUND:
            return None
        if resp.status != STATUS_OK:
            self._raise(resp)
        flags = struct.unpack(">I", resp.extras)[0] if len(resp.extras) >= 4 else 0
        return GetResult(resp.value, flags, resp.cas)

    def _store(self, opcode: int, key, value, flags: int, exptime: int,
               cas: int) -> int:
        extras = struct.pack(">II", flags, exptime)
        resp = self._call(opcode, self._key(key), self._val(value), extras,
                          cas)
        if resp.status != STATUS_OK:
            self._raise(resp)
        return resp.cas

    async def _store_async(self, opcode: int, key, value, flags: int,
                           exptime: int, cas: int) -> int:
        extras = struct.pack(">II", flags, exptime)
        resp = await self._call_async(opcode, self._key(key),
                                      self._val(value), extras, cas)
        if resp.status != STATUS_OK:
            self._raise(resp)
        return resp.cas

    async def set_async(self, key, value, flags: int = 0, exptime: int = 0,
                        cas: int = 0) -> int:
        return await self._store_async(OP_SET, key, value, flags, exptime,
                                       cas)

    def set(self, key, value, flags: int = 0, exptime: int = 0,
            cas: int = 0) -> int:
        """Returns the new cas. With cas != 0 this is a check-and-set
        (raises MemcacheError(STATUS_KEY_EXISTS) on conflict)."""
        return self._store(OP_SET, key, value, flags, exptime, cas)

    def add(self, key, value, flags: int = 0, exptime: int = 0) -> int:
        return self._store(OP_ADD, key, value, flags, exptime, 0)

    def replace(self, key, value, flags: int = 0, exptime: int = 0) -> int:
        return self._store(OP_REPLACE, key, value, flags, exptime, 0)

    def _concat(self, opcode: int, key, value) -> int:
        resp = self._call(opcode, self._key(key), self._val(value))
        if resp.status != STATUS_OK:
            self._raise(resp)
        return resp.cas

    def append(self, key, value) -> int:
        return self._concat(OP_APPEND, key, value)

    def prepend(self, key, value) -> int:
        return self._concat(OP_PREPEND, key, value)

    def delete(self, key) -> bool:
        resp = self._call(OP_DELETE, self._key(key))
        if resp.status == STATUS_KEY_NOT_FOUND:
            return False
        if resp.status != STATUS_OK:
            self._raise(resp)
        return True

    def _arith(self, opcode: int, key, delta: int, initial: int,
               exptime: int) -> int:
        extras = struct.pack(">QQI", delta, initial, exptime)
        resp = self._call(opcode, self._key(key), extras=extras)
        if resp.status != STATUS_OK:
            self._raise(resp)
        return struct.unpack(">Q", resp.value)[0]

    def incr(self, key, delta: int = 1, initial: int = 0,
             exptime: int = 0) -> int:
        return self._arith(OP_INCREMENT, key, delta, initial, exptime)

    def decr(self, key, delta: int = 1, initial: int = 0,
             exptime: int = 0) -> int:
        return self._arith(OP_DECREMENT, key, delta, initial, exptime)

    def touch(self, key, exptime: int) -> bool:
        resp = self._call(OP_TOUCH, self._key(key),
                          extras=struct.pack(">I", exptime))
        if resp.status == STATUS_KEY_NOT_FOUND:
            return False
        if resp.status != STATUS_OK:
            self._raise(resp)
        return True

    def version(self) -> str:
        resp = self._call(OP_VERSION)
        if resp.status != STATUS_OK:
            self._raise(resp)
        return resp.value.decode("latin1")

    def flush_all(self, delay: int = 0) -> None:
        extras = struct.pack(">I", delay) if delay else b""
        resp = self._call(OP_FLUSH, extras=extras)
        if resp.status != STATUS_OK:
            self._raise(resp)

    def noop(self) -> None:
        resp = self._call(OP_NOOP)
        if resp.status != STATUS_OK:
            self._raise(resp)

    def pipeline_get(self, keys: List) -> List[Optional[GetResult]]:
        """Batched multi-get: N GET requests in one write, N replies."""
        if not keys:
            return []
        opaques = []
        buf = IOBuf()
        for key in keys:
            opaque = next(self._opaque)
            opaques.append(opaque)
            buf.append(pack_request(OP_GET, self._key(key), opaque=opaque))
        batch = self._start(buf, len(keys))
        results = self._wait(batch, "memcache pipeline_get")
        out: List[Optional[GetResult]] = []
        for resp, opaque in zip(results, opaques):
            if resp.opaque != opaque:
                if batch.socket is not None:
                    batch.socket.set_failed(
                        ConnectionError("memcache reply desync"))
                raise MemcacheError(-1, "reply desync (opaque mismatch)")
            if resp.status == STATUS_KEY_NOT_FOUND:
                out.append(None)
            elif resp.status != STATUS_OK:
                self._raise(resp)
            else:
                flags = (struct.unpack(">I", resp.extras)[0]
                         if len(resp.extras) >= 4 else 0)
                out.append(GetResult(resp.value, flags, resp.cas))
        return out


_instance: Optional[MemcacheProtocol] = None


def ensure_registered() -> MemcacheProtocol:
    global _instance
    if _instance is None:
        _instance = MemcacheProtocol()
        register_protocol(_instance)
    return _instance
