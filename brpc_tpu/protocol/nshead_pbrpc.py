"""nova_pbrpc, public_pbrpc and ubrpc — the remaining nshead-family
protocols (reference: policy/nova_pbrpc_protocol.cpp,
policy/public_pbrpc_protocol.cpp, policy/ubrpc2pb_protocol.cpp).

All three ride the nshead framing (protocol/nshead.py) and install as
``ServerOptions(nshead_service=<adaptor>(svc))`` handlers, exactly as
the reference funnels them through NsheadService adaptors:

* **nova_pbrpc**: no meta at all — the method is addressed by INDEX in
  the nshead ``reserved`` field over the server's single service, the
  body is the bare pb request, and ``version & 0x1`` flags snappy.
  There is no correlation id on the wire, so responses match requests
  by connection order (the reference stores the correlation id on the
  socket and forbids CONNECTION_TYPE_SINGLE).
* **public_pbrpc**: body is a ``PublicPbrpcRequest`` pb envelope
  (requestHead + requestBody[service, method_id, id,
  serialized_request]); the ``id`` carries correlation. nshead id field
  is unused.
* **ubrpc (compack flavor)**: body is an mcpack object
  ``{content: [{service_name, method, id, params}]}``; responses carry
  ``{content: [{id, result|error:{code,message}}]}``. The reference
  additionally supports the "nested" flavor and mcpack_v2 — this
  implementation speaks the compack-object shape over our mcpack codec.
"""

from __future__ import annotations

import inspect
import itertools
import struct
from typing import Any, Dict, Optional

from brpc_tpu.protocol.mcpack import (McpackError, decode, encode,
                                      mcpack_to_pb, pb_to_mcpack)
from brpc_tpu.protocol.nshead import NsheadClient, NsheadMessage
from brpc_tpu.protocol.proto import public_pbrpc_meta_pb2 as ppb

NOVA_SNAPPY_COMPRESS_FLAG = 0x1
UBRPC_NSHEAD_VERSION = 1000


def _methods_in_order(service):
    return list(service.methods.values())


def _serialize_reply(r) -> bytes:
    if r is None:
        return b""
    if hasattr(r, "SerializeToString"):
        return r.SerializeToString()
    return bytes(r)


async def _invoke(method, raw_body, socket, request=None):
    """Shared guarded dispatch for the nshead-family adaptors: build the
    request (unless pre-built), run the handler, never let an exception
    escape into the framing layer (an unanswered FIFO slot desyncs every
    later reply on the connection). Returns (reply, cntl, error_text) —
    error_text None on success."""
    from brpc_tpu.rpc.controller import Controller
    cntl = Controller()
    cntl.remote_side = socket.remote_endpoint
    if request is None:
        if method.request_class is not None:
            request = method.request_class()
            try:
                request.ParseFromString(raw_body)
            except Exception as e:
                return None, cntl, f"bad request body: {e}"
        else:
            request = raw_body
    try:
        r = method.handler(cntl, request)
        if inspect.isawaitable(r):
            r = await r
    except Exception as e:
        return None, cntl, f"handler error: {e}"
    if cntl.failed():
        return None, cntl, cntl.error_text
    return r, cntl, None


# ------------------------------------------------------------------ nova

def nova_adaptor(service):
    """Serve a Service over nova_pbrpc. The method index in
    ``head.reserved`` selects methods in registration order
    (nova_pbrpc_protocol.cpp ParseNsheadMeta: first service, method by
    |reserved|). Errors cannot be reported on the wire — the reference
    closes the connection; we do the same by returning no reply."""
    methods = _methods_in_order(service)

    async def handler(socket, msg: NsheadMessage):
        if not 0 <= msg.reserved < len(methods):
            socket.set_failed(ConnectionError(
                f"nova: no method at index {msg.reserved}"))
            return None
        body = msg.body
        if msg.version & NOVA_SNAPPY_COMPRESS_FLAG:
            # nova_pbrpc_protocol.cpp: request body is raw snappy.
            # ANY decode failure must drop the connection — an
            # unanswered FIFO slot hands the next reply to this waiter
            from brpc_tpu.butil import snappy_codec
            try:
                body = snappy_codec.decompress_auto(bytes(body))
            except Exception as e:  # noqa: BLE001 - see comment above
                socket.set_failed(ConnectionError(
                    f"nova: corrupt snappy body: {e}"))
                return None
        r, _cntl, err = await _invoke(methods[msg.reserved], body,
                                      socket)
        if err is not None:
            # nova can not send feedback on failure: close the conn
            # (nova_pbrpc_protocol.cpp CloseConnection) — an unanswered
            # slot would silently hand the NEXT reply to this waiter
            socket.set_failed(ConnectionError(f"nova: {err}"))
            return None
        return NsheadMessage(_serialize_reply(r), id=msg.id,
                             log_id=msg.log_id)

    return handler


class NovaClient(NsheadClient):
    """Call a nova_pbrpc server: method by index, pb or bytes payload.
    Matching is by connection order (pipelined FIFO), the same
    single-conn-forbidden model as the reference."""

    def call_method(self, method_index: int, request, log_id: int = 0,
                    snappy: bool = False):
        body = _serialize_reply(request)
        version = 0
        if snappy:
            # nova_pbrpc_protocol.cpp: raw-snappy body, flagged in the
            # nshead version field
            from brpc_tpu.butil import snappy_codec
            body = snappy_codec.compress_auto(body)
            version = NOVA_SNAPPY_COMPRESS_FLAG
        reply = self.call(NsheadMessage(body, version=version,
                                        log_id=log_id,
                                        reserved=method_index))
        rbody = reply.body
        if reply.version & NOVA_SNAPPY_COMPRESS_FLAG:
            # symmetric: a server may flag its response compressed
            from brpc_tpu.butil import snappy_codec
            rbody = snappy_codec.decompress_auto(bytes(rbody))
        return rbody


# ---------------------------------------------------------- public_pbrpc

def public_pbrpc_adaptor(service):
    """Serve a Service over public_pbrpc: requestBody.method_id indexes
    methods in registration order; the body envelope's id ties the
    response (public_pbrpc_protocol.cpp ProcessPublicPbrpcRequest)."""
    methods = _methods_in_order(service)

    async def handler(socket, msg: NsheadMessage):
        req = ppb.PublicPbrpcRequest()
        try:
            req.ParseFromString(msg.body)
        except Exception:
            socket.set_failed(ConnectionError("public_pbrpc: bad envelope"))
            return None
        res = ppb.PublicPbrpcResponse()
        res.responseHead.code = 0
        res.responseHead.from_host = "brpc-tpu"
        for body in req.requestBody:
            rb = res.responseBody.add()
            rb.id = body.id
            if not 0 <= body.method_id < len(methods):
                rb.error = 1002
                continue
            r, cntl, err = await _invoke(methods[body.method_id],
                                         bytes(body.serialized_request),
                                         socket)
            if err is not None:
                # per-body error channel: one bad request must not
                # drop the whole envelope (that desyncs FIFO matching)
                rb.error = cntl.error_code or 2001
            else:
                rb.serialized_response = _serialize_reply(r)
        return NsheadMessage(res.SerializeToString(), id=msg.id,
                             log_id=msg.log_id)

    return handler


class PublicPbrpcClient(NsheadClient):
    _ids = itertools.count(1)

    def call_method(self, service: str, method_id: int, request,
                    log_id: int = 0) -> bytes:
        """Returns the serialized response bytes; raises on wire error."""
        env = ppb.PublicPbrpcRequest()
        env.requestHead.log_id = log_id
        body = env.requestBody.add()
        body.service = service
        body.method_id = method_id
        body.id = next(self._ids)
        body.serialized_request = _serialize_reply(request)
        reply = self.call(NsheadMessage(env.SerializeToString(),
                                        log_id=log_id))
        res = ppb.PublicPbrpcResponse()
        res.ParseFromString(reply.body)
        if not res.responseBody:
            raise ConnectionError("public_pbrpc: empty response envelope")
        rb = res.responseBody[0]
        if rb.id != body.id:
            raise ConnectionError(
                f"public_pbrpc: response id {rb.id} != request id {body.id}")
        if rb.error:
            raise ConnectionError(f"public_pbrpc: remote error {rb.error}")
        return bytes(rb.serialized_response)


# ------------------------------------------------------------------ ubrpc

def ubrpc_adaptor(service):
    """Serve a Service over ubrpc's compack-object shape
    (ubrpc2pb_protocol.cpp ParseNsheadMeta): request.content[0] holds
    service_name/method/id/params; params maps to the pb request via the
    mcpack bridge. Error replies carry {id, error:{code,message}}."""

    async def handler(socket, msg: NsheadMessage):

        def error_reply(corr_id, code, text):
            return NsheadMessage(encode({"content": [
                {"id": corr_id,
                 "error": {"code": code, "message": text}}]}),
                id=msg.id, version=UBRPC_NSHEAD_VERSION, log_id=msg.log_id)

        try:
            doc = decode(msg.body)
        except McpackError as e:
            return error_reply(0, 2001, f"bad compack body: {e}")
        content = doc.get("content")
        if not isinstance(content, list) or not content:
            return error_reply(0, 2001, "missing request.content")
        item = content[0]
        corr_id = int(item.get("id", 0))
        method_name = str(item.get("method", ""))
        if not method_name:
            return error_reply(corr_id, 1002, "missing method")
        method = service.methods.get(method_name)
        if method is None:
            return error_reply(corr_id, 1002,
                               f"unknown method {method_name!r}")
        params = item.get("params")
        if not isinstance(params, dict):
            return error_reply(corr_id, 2001, "missing params object")
        if method.request_class is not None:
            request = method.request_class()
            try:
                mcpack_to_pb(params, request)
            except Exception as e:
                return error_reply(corr_id, 2001, f"bad params: {e}")
        else:
            request = params
        r, cntl, err = await _invoke(method, b"", socket, request=request)
        if err is not None:
            return error_reply(corr_id, cntl.error_code or 2001, err)
        if hasattr(r, "ListFields"):
            result: Any = pb_to_mcpack(r)
        elif isinstance(r, (bytes, bytearray, memoryview)):
            result = bytes(r)
        elif isinstance(r, dict) or r is None:
            result = r or {}
        else:
            result = r
        return NsheadMessage(encode({"content": [
            {"id": corr_id, "result": result}]}),
            id=msg.id, version=UBRPC_NSHEAD_VERSION, log_id=msg.log_id)

    return handler


class UbrpcClient(NsheadClient):
    _ids = itertools.count(1)

    def call_method(self, service_name: str, method: str,
                    params: Dict[str, Any] | Any, log_id: int = 0):
        """params: a dict (or pb message, converted via the bridge).
        Returns the ``result`` value; raises on a remote error."""
        if hasattr(params, "ListFields"):
            params = pb_to_mcpack(params)
        corr_id = next(self._ids)
        body = encode({"content": [{
            "service_name": service_name, "method": method,
            "id": corr_id, "params": params}]})
        reply = self.call(NsheadMessage(
            body, version=UBRPC_NSHEAD_VERSION, log_id=log_id))
        doc = decode(reply.body)
        content = doc.get("content") or [{}]
        item = content[0]
        # surface a remote error FIRST: pre-dispatch server errors
        # (undecodable body) legitimately carry id 0, and an id-mismatch
        # complaint would mask the actual diagnostic
        err = item.get("error")
        if err:
            raise ConnectionError(
                f"ubrpc: remote error {err.get('code')}: "
                f"{err.get('message')}")
        got_id = int(item.get("id", -1))
        if got_id != corr_id:
            raise ConnectionError(
                f"ubrpc: response id {got_id} != request id {corr_id}")
        return item.get("result")
