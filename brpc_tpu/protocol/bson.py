"""Minimal BSON codec for the mongo wire adaptor (the reference links a
bson dependency for policy/mongo_protocol.cpp; this is a from-scratch
subset covering the types mongo commands actually use).

Supported: double, string, document, array, binary, bool, null, int32,
int64, ObjectId (as 12 raw bytes), UTC datetime (as int64 ms).
Python mapping: dict, list, str, bytes (binary subtype 0), bool, None,
int (int32 when it fits else int64), float, ObjectId."""

from __future__ import annotations

import struct
from typing import Any, Dict, List, Tuple

_MAX_DEPTH = 64


class ObjectId:
    __slots__ = ("raw",)

    def __init__(self, raw: bytes):
        if len(raw) != 12:
            raise ValueError("ObjectId must be 12 bytes")
        self.raw = bytes(raw)

    def __eq__(self, other):
        return isinstance(other, ObjectId) and self.raw == other.raw

    def __hash__(self):
        return hash(self.raw)

    def __repr__(self):
        return f"ObjectId({self.raw.hex()})"


class DateTimeMs(int):
    """UTC datetime as milliseconds since epoch (wire type 0x09)."""


class BsonError(Exception):
    pass


def _encode_value(key: bytes, v, depth: int) -> bytes:
    if depth > _MAX_DEPTH:
        raise BsonError("document nesting too deep")
    if isinstance(v, float):
        return b"\x01" + key + b"\x00" + struct.pack("<d", v)
    if isinstance(v, str):
        s = v.encode()
        return b"\x02" + key + b"\x00" + struct.pack("<i", len(s) + 1) + s + b"\x00"
    if isinstance(v, dict):
        return b"\x03" + key + b"\x00" + encode_doc(v, depth + 1)
    if isinstance(v, (list, tuple)):
        arr = {str(i): x for i, x in enumerate(v)}
        return b"\x04" + key + b"\x00" + encode_doc(arr, depth + 1)
    if isinstance(v, (bytes, bytearray, memoryview)):
        b = bytes(v)
        return b"\x05" + key + b"\x00" + struct.pack("<ib", len(b), 0) + b
    if isinstance(v, ObjectId):
        return b"\x07" + key + b"\x00" + v.raw
    if isinstance(v, bool):
        return b"\x08" + key + b"\x00" + (b"\x01" if v else b"\x00")
    if isinstance(v, DateTimeMs):
        return b"\x09" + key + b"\x00" + struct.pack("<q", int(v))
    if v is None:
        return b"\x0a" + key + b"\x00"
    if isinstance(v, int):
        if -(1 << 31) <= v < (1 << 31):
            return b"\x10" + key + b"\x00" + struct.pack("<i", v)
        return b"\x12" + key + b"\x00" + struct.pack("<q", v)
    raise BsonError(f"cannot encode {type(v)!r}")


def encode_doc(doc: Dict[str, Any], depth: int = 0) -> bytes:
    body = b"".join(_encode_value(k.encode(), v, depth)
                    for k, v in doc.items())
    return struct.pack("<i", len(body) + 5) + body + b"\x00"


def _read_cstring(data: bytes, pos: int) -> Tuple[str, int]:
    end = data.find(b"\x00", pos)
    if end < 0:
        raise BsonError("unterminated cstring")
    return data[pos:end].decode("utf-8", "replace"), end + 1


def _decode_value(t: int, data: bytes, pos: int, depth: int):
    if t == 0x01:
        return struct.unpack_from("<d", data, pos)[0], pos + 8
    if t == 0x02:
        n = struct.unpack_from("<i", data, pos)[0]
        if n < 1 or pos + 4 + n > len(data):
            raise BsonError("bad string length")
        return data[pos + 4:pos + 4 + n - 1].decode("utf-8", "replace"), \
            pos + 4 + n
    if t == 0x03:
        doc, end = decode_doc(data, pos, depth + 1)
        return doc, end
    if t == 0x04:
        doc, end = decode_doc(data, pos, depth + 1)
        return [doc[k] for k in sorted(doc, key=lambda x: int(x) if
                                       x.isdigit() else 0)], end
    if t == 0x05:
        n, _subtype = struct.unpack_from("<ib", data, pos)
        if n < 0 or pos + 5 + n > len(data):
            raise BsonError("bad binary length")
        return bytes(data[pos + 5:pos + 5 + n]), pos + 5 + n
    if t == 0x07:
        return ObjectId(data[pos:pos + 12]), pos + 12
    if t == 0x08:
        return data[pos:pos + 1] == b"\x01", pos + 1
    if t == 0x09:
        return DateTimeMs(struct.unpack_from("<q", data, pos)[0]), pos + 8
    if t == 0x0a:
        return None, pos
    if t == 0x10:
        return struct.unpack_from("<i", data, pos)[0], pos + 4
    if t == 0x11:  # timestamp: surface as int64
        return struct.unpack_from("<q", data, pos)[0], pos + 8
    if t == 0x12:
        return struct.unpack_from("<q", data, pos)[0], pos + 8
    raise BsonError(f"unsupported bson type 0x{t:02x}")


def decode_doc(data: bytes, pos: int = 0, depth: int = 0
               ) -> Tuple[Dict[str, Any], int]:
    """Decode one document at ``pos``; returns (doc, end_pos)."""
    if depth > _MAX_DEPTH:
        raise BsonError("document nesting too deep")
    if pos + 4 > len(data):
        raise BsonError("truncated document")
    size = struct.unpack_from("<i", data, pos)[0]
    if size < 5 or pos + size > len(data):
        raise BsonError("bad document size")
    end = pos + size
    cur = pos + 4
    out: Dict[str, Any] = {}
    while cur < end - 1:
        t = data[cur]
        key, cur = _read_cstring(data, cur + 1)
        value, cur = _decode_value(t, data, cur, depth)
        out[key] = value
    if data[end - 1:end] != b"\x00":
        raise BsonError("document missing terminator")
    return out, end
