"""mcpack: typed named-item pack format + pb bridge (re-design of the
reference's src/mcpack2pb/, 4.4k LoC — mcpack parser/serializer plus a
protoc plugin generating per-message converters; here the converters are
dynamic over descriptors, like json_format).

Wire layout (v2-inspired, documented here rather than byte-compatible
with legacy baidu mcpack — the reference's bridge targets baidu-internal
services that do not exist outside):

  item   := type:u8 name_len:u8 [name bytes (no NUL)] content
  OBJECT (0x10) / ARRAY (0x20): content = count:u32 items*
  STRING (0x50): content = len:u32 utf8 bytes
  BINARY (0x60): content = len:u32 raw bytes
  INT64  (0x11): content = i64 LE     UINT64 (0x12): u64 LE
  DOUBLE (0x13): content = f64 LE     BOOL   (0x14): u8
  NULL   (0x15): no content
Array elements have name_len 0."""

from __future__ import annotations

import struct
from typing import Any, Dict, List, Tuple

OBJECT = 0x10
ARRAY = 0x20
STRING = 0x50
BINARY = 0x60
INT64 = 0x11
UINT64 = 0x12
DOUBLE = 0x13
BOOL = 0x14
NULL = 0x15

_MAX_DEPTH = 64
_MAX_COUNT = 1 << 24


class McpackError(Exception):
    pass


# ----------------------------------------------------------------- encode

def _encode_item(name: bytes, v, depth: int) -> bytes:
    if depth > _MAX_DEPTH:
        raise McpackError("nesting too deep")
    if len(name) > 255:
        raise McpackError("name too long")
    head = bytes([0, len(name)]) + name   # type patched below
    if isinstance(v, bool):
        return bytes([BOOL]) + head[1:] + (b"\x01" if v else b"\x00")
    if isinstance(v, int):
        if -(1 << 63) <= v < (1 << 63):
            return bytes([INT64]) + head[1:] + struct.pack("<q", v)
        if 0 <= v < (1 << 64):
            return bytes([UINT64]) + head[1:] + struct.pack("<Q", v)
        raise McpackError("integer out of 64-bit range")
    if isinstance(v, float):
        return bytes([DOUBLE]) + head[1:] + struct.pack("<d", v)
    if isinstance(v, str):
        b = v.encode()
        return bytes([STRING]) + head[1:] + struct.pack("<I", len(b)) + b
    if isinstance(v, (bytes, bytearray, memoryview)):
        b = bytes(v)
        return bytes([BINARY]) + head[1:] + struct.pack("<I", len(b)) + b
    if v is None:
        return bytes([NULL]) + head[1:]
    if isinstance(v, dict):
        items = b"".join(_encode_item(str(k).encode(), val, depth + 1)
                         for k, val in v.items())
        return bytes([OBJECT]) + head[1:] + struct.pack("<I", len(v)) + items
    if isinstance(v, (list, tuple)):
        items = b"".join(_encode_item(b"", val, depth + 1) for val in v)
        return bytes([ARRAY]) + head[1:] + struct.pack("<I", len(v)) + items
    raise McpackError(f"cannot encode {type(v)!r}")


def encode(doc: Dict[str, Any]) -> bytes:
    """Top level is an unnamed OBJECT."""
    return _encode_item(b"", doc, 0)


# ----------------------------------------------------------------- decode

def _decode_item(data: bytes, pos: int, depth: int) -> Tuple[bytes, Any, int]:
    if depth > _MAX_DEPTH:
        raise McpackError("nesting too deep")
    if pos + 2 > len(data):
        raise McpackError("truncated item head")
    t = data[pos]
    name_len = data[pos + 1]
    pos += 2
    if pos + name_len > len(data):
        raise McpackError("truncated name")
    name = data[pos:pos + name_len]
    pos += name_len
    if t == BOOL:
        if pos + 1 > len(data):
            raise McpackError("truncated bool")
        return name, data[pos] != 0, pos + 1
    if t == INT64:
        if pos + 8 > len(data):
            raise McpackError("truncated int64")
        return name, struct.unpack_from("<q", data, pos)[0], pos + 8
    if t == UINT64:
        if pos + 8 > len(data):
            raise McpackError("truncated uint64")
        return name, struct.unpack_from("<Q", data, pos)[0], pos + 8
    if t == DOUBLE:
        if pos + 8 > len(data):
            raise McpackError("truncated double")
        return name, struct.unpack_from("<d", data, pos)[0], pos + 8
    if t == NULL:
        return name, None, pos
    if t in (STRING, BINARY):
        if pos + 4 > len(data):
            raise McpackError("truncated length")
        n = struct.unpack_from("<I", data, pos)[0]
        pos += 4
        if n > len(data) - pos:
            raise McpackError("truncated content")
        raw = data[pos:pos + n]
        return name, (raw.decode("utf-8", "replace") if t == STRING
                      else bytes(raw)), pos + n
    if t in (OBJECT, ARRAY):
        if pos + 4 > len(data):
            raise McpackError("truncated count")
        count = struct.unpack_from("<I", data, pos)[0]
        pos += 4
        if count > _MAX_COUNT:
            raise McpackError("bad count")
        if t == OBJECT:
            obj: Dict[str, Any] = {}
            for _ in range(count):
                n2, v, pos = _decode_item(data, pos, depth + 1)
                obj[n2.decode("utf-8", "replace")] = v
            return name, obj, pos
        arr: List[Any] = []
        for _ in range(count):
            _n, v, pos = _decode_item(data, pos, depth + 1)
            arr.append(v)
        return name, arr, pos
    raise McpackError(f"unknown type 0x{t:02x}")


def decode(data: bytes) -> Dict[str, Any]:
    _name, v, pos = _decode_item(data, 0, 0)
    if not isinstance(v, dict):
        raise McpackError("top level is not an object")
    if pos != len(data):
        raise McpackError(f"{len(data) - pos} trailing bytes")
    return v


# ------------------------------------------------------------- pb bridge

def pb_to_mcpack(msg) -> Dict[str, Any]:
    """protobuf message -> mcpack map (the generated serializer half of
    mcpack2pb/generator.cpp, done dynamically over descriptors)."""
    out: Dict[str, Any] = {}
    for field, value in msg.ListFields():
        out[field.name] = _pb_value(field, value)
    return out


def _pb_value(field, value):
    if field.is_repeated:
        return [_pb_scalar(field, v) for v in value]
    return _pb_scalar(field, value)


def _pb_scalar(field, v):
    if field.type == field.TYPE_MESSAGE:
        return pb_to_mcpack(v)
    if field.type == field.TYPE_BYTES:
        return bytes(v)
    if field.type == field.TYPE_ENUM:
        return int(v)
    return v


def mcpack_to_pb(doc: Dict[str, Any], msg) -> None:
    """mcpack map -> protobuf message in place (the parse half)."""
    for field in msg.DESCRIPTOR.fields:
        if field.name not in doc:
            continue
        v = doc[field.name]
        if field.is_repeated:
            target = getattr(msg, field.name)
            for item in (v if isinstance(v, list) else [v]):
                if field.type == field.TYPE_MESSAGE:
                    mcpack_to_pb(item, target.add())
                else:
                    target.append(_coerce(field, item))
        elif field.type == field.TYPE_MESSAGE:
            mcpack_to_pb(v, getattr(msg, field.name))
        else:
            setattr(msg, field.name, _coerce(field, v))


def _coerce(field, v):
    if field.type == field.TYPE_BYTES:
        return v if isinstance(v, bytes) else str(v).encode()
    if field.type in (field.TYPE_STRING,):
        return v if isinstance(v, str) else \
            v.decode("utf-8", "replace") if isinstance(v, bytes) else str(v)
    if field.type in (field.TYPE_FLOAT, field.TYPE_DOUBLE):
        return float(v)
    if field.type == field.TYPE_BOOL:
        return bool(v)
    return int(v)


# ------------------------------------------------- nshead_mcpack adaptor

def nshead_mcpack_adaptor(service):
    """Adapt a pb/bytes Service to nshead+mcpack framing
    (policy/nshead_mcpack_protocol.cpp + nshead_pb_service_adaptor):
    request body = mcpack {"method": str, "request": map-or-binary},
    response body = {"error_code", "error_text", "response"}.
    Install as ``ServerOptions(nshead_service=nshead_mcpack_adaptor(svc))``.
    """
    import inspect

    async def handler(socket, msg):
        try:
            doc = decode(msg.body)
            method = service.methods.get(str(doc.get("method", "")))
            if method is None:
                return encode({"error_code": 1002,
                               "error_text": f"unknown method "
                                             f"{doc.get('method')!r}"})
            req_part = doc.get("request", {})
            if method.request_class is not None and \
                    isinstance(req_part, dict):
                request = method.request_class()
                mcpack_to_pb(req_part, request)
            elif isinstance(req_part, (bytes, bytearray)):
                request = bytes(req_part)
            else:
                request = req_part
            from brpc_tpu.rpc.controller import Controller
            cntl = Controller()
            cntl.remote_side = socket.remote_endpoint
            r = method.handler(cntl, request)
            if inspect.isawaitable(r):
                r = await r
            if cntl.failed():
                return encode({"error_code": cntl.error_code,
                               "error_text": cntl.error_text})
            if hasattr(r, "ListFields"):
                return encode({"error_code": 0, "response": pb_to_mcpack(r)})
            if isinstance(r, (bytes, bytearray, memoryview)):
                return encode({"error_code": 0, "response": bytes(r)})
            return encode({"error_code": 0,
                           "response": r if r is not None else {}})
        except McpackError as e:
            return encode({"error_code": 1003,
                           "error_text": f"bad mcpack request: {e}"})
        except Exception as e:
            return encode({"error_code": 2001,
                           "error_text": f"handler error: {e}"})

    return handler
