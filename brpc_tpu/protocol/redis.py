"""Redis protocol (RESP2): pipelined client + server-side command registry.

Re-design of the reference's redis support (src/brpc/redis.{h,cpp} —
RedisService registry redis.h:240, command handlers; wire codec + server
dispatch policy/redis_protocol.cpp:428; client pipelining rides the
socket's FIFO write order exactly like pipelined_count on Socket).

Client replies carry no correlation id: RESP is strictly FIFO per
connection, so the client keeps an ordered queue of outstanding batches
and the response processor fills them in parse order. The server side
must answer in request order too, so commands drain through a per-socket
serial fiber (same pattern as HTTP/1.1 pipelining in protocol/http.py).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

from brpc_tpu.butil.endpoint import EndPoint
from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.fiber import TaskControl
from brpc_tpu.protocol.registry import (
    PARSE_NOT_ENOUGH_DATA, PARSE_OK, PARSE_TRY_OTHERS, Protocol,
    register_protocol,
)
from brpc_tpu.transport.pipelined import PipelinedClient

_MAX_LINE = 1 << 20            # cap unterminated scans (flood guard)


class RedisStatus(str):
    """A +simple-string reply ("OK", "PONG"): distinct from bulk data."""


class RedisError(Exception):
    """An -error reply. Returned (not raised) inside pipeline results."""

    def __eq__(self, other):
        return isinstance(other, RedisError) and self.args == other.args

    def __hash__(self):
        return hash(("RedisError",) + self.args)


class _NeedMore(Exception):
    pass


class _BadWire(Exception):
    pass


# ------------------------------------------------------------------ codec

def encode_command(args) -> bytes:
    """Multi-bulk encode one command: ["SET", "k", 1] -> *3$3SET$1k$11."""
    out = [b"*%d\r\n" % len(args)]
    for a in args:
        if isinstance(a, str):
            a = a.encode()
        elif isinstance(a, bool):
            # bool before int: repr() would yield b"True"/b"False"
            a = b"1" if a else b"0"
        elif isinstance(a, (int, float)):
            a = repr(a).encode()
        elif not isinstance(a, (bytes, bytearray, memoryview)):
            raise TypeError(f"bad redis argument type {type(a)!r}")
        a = bytes(a)
        out.append(b"$%d\r\n%s\r\n" % (len(a), a))
    return b"".join(out)


def encode_reply(value) -> bytes:
    """Server->client encoding for handler return values."""
    if isinstance(value, RedisStatus):
        return b"+%s\r\n" % str(value).encode()
    if isinstance(value, RedisError):
        msg = value.args[0] if value.args else "ERR"
        return b"-%s\r\n" % str(msg).encode()
    if isinstance(value, bool):
        return b":%d\r\n" % int(value)
    if isinstance(value, int):
        return b":%d\r\n" % value
    if value is None:
        return b"$-1\r\n"
    if isinstance(value, str):
        value = value.encode()
    if isinstance(value, (bytes, bytearray, memoryview)):
        value = bytes(value)
        return b"$%d\r\n%s\r\n" % (len(value), value)
    if isinstance(value, (list, tuple)):
        return b"*%d\r\n" % len(value) + b"".join(encode_reply(v) for v in value)
    raise TypeError(f"cannot encode redis reply of type {type(value)!r}")


def parse_value(data: bytes, pos: int, inline_ok: bool = False,
                depth: int = 0) -> Tuple[Any, int]:
    """Parse one RESP value starting at ``pos``. Raises _NeedMore when the
    bytes are a valid prefix, _BadWire when they can never be RESP."""
    if depth > 32:
        # nesting is attacker-controlled ("*1\r\n" repeated): cap it so a
        # hostile peer cannot blow the Python stack (RecursionError would
        # escape the _NeedMore/_BadWire handling in parse())
        raise _BadWire("RESP nesting too deep")
    if pos >= len(data):
        raise _NeedMore
    t = data[pos:pos + 1]
    eol = data.find(b"\r\n", pos)
    if eol < 0:
        if len(data) - pos > _MAX_LINE:
            raise _BadWire("unterminated line")
        raise _NeedMore
    line = data[pos + 1:eol]
    nxt = eol + 2
    if t == b"+":
        return RedisStatus(line.decode("latin1")), nxt
    if t == b"-":
        return RedisError(line.decode("latin1")), nxt
    if t == b":":
        try:
            return int(line), nxt
        except ValueError:
            raise _BadWire("bad integer")
    if t == b"$":
        try:
            n = int(line)
        except ValueError:
            raise _BadWire("bad bulk length")
        if n == -1:
            return None, nxt
        if n < 0:
            raise _BadWire("negative bulk length")
        if len(data) < nxt + n + 2:
            raise _NeedMore
        if data[nxt + n:nxt + n + 2] != b"\r\n":
            raise _BadWire("bulk not CRLF-terminated")
        return data[nxt:nxt + n], nxt + n + 2
    if t == b"*":
        try:
            n = int(line)
        except ValueError:
            raise _BadWire("bad array length")
        if n == -1:
            return None, nxt
        if n < 0:
            raise _BadWire("negative array length")
        out = []
        for _ in range(n):
            v, nxt = parse_value(data, nxt, inline_ok=False, depth=depth + 1)
            out.append(v)
        return out, nxt
    if inline_ok:
        # telnet-style inline command: whole line is whitespace-split words
        words = data[pos:eol].split()
        if not words:
            raise _BadWire("empty inline command")
        return [bytes(w) for w in words], nxt
    raise _BadWire(f"bad RESP type byte {t!r}")


# ----------------------------------------------------------------- server

class RedisService:
    """Server-side command table (redis.h:240 RedisService +
    RedisCommandHandler). Handlers take (cntl_socket, args) where args is
    the full command as a list of bytes (args[0] = command name) and
    return any value ``encode_reply`` accepts."""

    def __init__(self):
        self._handlers: Dict[str, Callable] = {}

    def add_command_handler(self, name: str, fn: Callable) -> None:
        self._handlers[name.upper()] = fn

    def command(self, name: Optional[str] = None):
        def deco(fn):
            self.add_command_handler(name or fn.__name__, fn)
            return fn
        return deco

    def find(self, name: bytes) -> Optional[Callable]:
        return self._handlers.get(name.decode("latin1").upper())


# --------------------------------------------------------------- protocol

class _Burst(list):
    """Several RESP values cut from one peek (a pipelined burst arriving
    in a single read): delivered as one parse result so N messages cost
    one O(bytes) pass instead of N re-peeks (O(N^2)); process_inline
    fans them back out in order."""


class RedisProtocol(Protocol):
    name = "redis"

    # ---------------------------------------------------------------- parse
    def parse(self, portal, socket) -> Tuple[str, object]:
        first = portal.peek_bytes(1)
        seen = socket.user_data.get("redis_seen", False)
        is_client = "redis_client" in socket.user_data
        if not first:
            return PARSE_NOT_ENOUGH_DATA, None
        if first not in (b"*", b"+", b"-", b":", b"$") and not (seen or is_client):
            # inline commands are only accepted once the peer has already
            # spoken RESP on this connection — otherwise any text protocol
            # would false-match here
            return PARSE_TRY_OTHERS, None
        data = portal.peek_bytes(portal.size)
        values: List = []
        consumed = 0
        while consumed < len(data):
            try:
                value, consumed = parse_value(data, consumed,
                                              inline_ok=not is_client)
                values.append(value)
            except _NeedMore:
                break
            except _BadWire:
                if values or seen or is_client:
                    # mid-stream corruption on an established redis conn:
                    # fail the connection rather than let another protocol
                    # eat it
                    socket.set_failed(ConnectionError("corrupt RESP stream"))
                    return PARSE_NOT_ENOUGH_DATA, None
                return PARSE_TRY_OTHERS, None
        if not values:
            return PARSE_NOT_ENOUGH_DATA, None
        socket.user_data["redis_seen"] = True
        portal.pop_front(consumed)
        if len(values) == 1:
            return PARSE_OK, values[0]
        return PARSE_OK, _Burst(values)

    # -------------------------------------------------------------- process
    def process_inline(self, msg, socket) -> bool:
        """Both sides are order-critical: client replies fill the FIFO
        batch queue (cheap, done right here); server commands drain
        through one serial fiber per connection."""
        vals = msg if isinstance(msg, _Burst) else (msg,)
        client = socket.user_data.get("redis_client")
        if client is not None:
            for v in vals:
                client._on_reply(socket, v)
            return True
        from brpc_tpu.transport.input_messenger import process_in_parse_order
        for v in vals:
            process_in_parse_order(socket, "redis", v, self._run_command)
        return True

    async def _run_command(self, cmd, socket):
        import inspect
        import time
        server = socket.user_data.get("server")
        service: Optional[RedisService] = (
            getattr(server.options, "redis_service", None)
            if server is not None else None)
        if service is None:
            socket.write(_reply_buf(RedisError(
                "ERR this server has no redis_service installed")))
            return
        if not isinstance(cmd, list) or not cmd or \
                not all(isinstance(a, bytes) for a in cmd):
            socket.write(_reply_buf(RedisError("ERR bad command frame")))
            return
        handler = service.find(cmd[0])
        name = cmd[0].decode("latin1").upper()
        if handler is None:
            if name == "PING":
                socket.write(_reply_buf(RedisStatus("PONG")))
                return
            socket.write(_reply_buf(RedisError(
                f"ERR unknown command '{name}'")))
            return
        cost = server.on_request_start(f"redis.{name}")
        if not cost:
            socket.write(_reply_buf(RedisError("ERR max_concurrency reached")))
            return
        t0 = time.monotonic_ns()
        error = False
        try:
            r = handler(socket, cmd)
            if inspect.isawaitable(r):
                r = await r
            out = _reply_buf(r)
        except Exception as e:
            error = True
            out = _reply_buf(RedisError(f"ERR handler error: {e}"))
        server.on_request_end(f"redis.{name}",
                              (time.monotonic_ns() - t0) / 1e3, error, cost)
        socket.write(out)

    def process(self, msg, socket):
        # everything is order-critical and consumed by process_inline
        raise AssertionError("redis messages are processed inline")


def _reply_buf(value) -> IOBuf:
    buf = IOBuf()
    buf.append(encode_reply(value))
    return buf


# ---------------------------------------------------------------- client

class RedisClient(PipelinedClient):
    """Pipelined RESP client over one connection.

    ``execute`` sends one command and returns its reply (raising
    RedisError replies); ``pipeline`` sends N commands in one write and
    returns N replies (RedisError instances returned in-place). Both have
    ``_async`` variants for fiber contexts."""

    user_data_key = "redis_client"

    def __init__(self, address: str | EndPoint, password: Optional[str] = None,
                 db: Optional[int] = None, timeout_s: float = 5.0,
                 control: Optional[TaskControl] = None):
        super().__init__(address, ensure_registered(), timeout_s=timeout_s,
                         control=control)
        self._password = password
        self._db = db

    def _hello_commands(self) -> List[bytes]:
        hello = []
        if self._password is not None:
            hello.append(encode_command(["AUTH", self._password]))
        if self._db is not None:
            hello.append(encode_command(["SELECT", self._db]))
        return hello

    def _check_hello_reply(self, reply) -> None:
        if isinstance(reply, RedisError):
            raise reply

    def _encode_batch(self, cmds: List[List]) -> IOBuf:
        buf = IOBuf()
        for cmd in cmds:
            buf.append(encode_command(cmd))
        return buf

    @staticmethod
    def _one(results: List):
        v = results[0]
        if isinstance(v, RedisError):
            raise v
        return v

    # ----------------------------------------------------------------- api
    def execute(self, *args):
        batch = self._start(self._encode_batch([list(args)]), 1)
        return self._one(self._wait(batch, f"redis {args[0]!r}"))

    def pipeline(self, cmds: List[List]) -> List:
        if not cmds:
            return []
        batch = self._start(self._encode_batch(cmds), len(cmds))
        return self._wait(batch, "redis pipeline")

    async def execute_async(self, *args):
        batch = self._start(self._encode_batch([list(args)]), 1)
        return self._one(await self._wait_async(batch, f"redis {args[0]!r}"))

    async def pipeline_async(self, cmds: List[List]) -> List:
        if not cmds:
            return []
        batch = self._start(self._encode_batch(cmds), len(cmds))
        return await self._wait_async(batch, "redis pipeline")


_instance: Optional[RedisProtocol] = None


def ensure_registered() -> RedisProtocol:
    global _instance
    if _instance is None:
        _instance = RedisProtocol()
        register_protocol(_instance)
    return _instance
