"""Mongo wire protocol server adaptor (policy/mongo_protocol.cpp:298,
mongo_head.h, mongo_service_adaptor.h): speak enough OP_MSG / OP_QUERY
for drivers and `mongosh`-style clients to issue commands at a brpc_tpu
server; the user supplies a MongoServiceAdaptor mapping command
documents to reply documents.

Wire: little-endian header {messageLength, requestID, responseTo,
opCode}; OP_MSG (2013) = flagBits:u32 + section kind 0 (one BSON doc);
OP_QUERY (2004, legacy handshake) = flags, fullCollectionName cstring,
numberToSkip, numberToReturn, query doc — answered with OP_REPLY (1)."""

from __future__ import annotations

import inspect
import struct
import threading
import time
from typing import Callable, Dict, Optional, Tuple

from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.protocol import bson
from brpc_tpu.protocol.registry import (
    PARSE_NOT_ENOUGH_DATA, PARSE_OK, PARSE_TRY_OTHERS, Protocol,
    register_protocol,
)

_HDR = struct.Struct("<iiii")
OP_REPLY = 1
OP_QUERY = 2004
OP_MSG = 2013
_MAX_MESSAGE = 48 << 20
_KNOWN_OPS = (OP_REPLY, OP_QUERY, OP_MSG, 2001, 2002, 2005, 2006, 2010,
              2011, 2012)


class MongoMessage:
    __slots__ = ("request_id", "response_to", "op_code", "flags", "doc",
                 "collection")

    def __init__(self, request_id, response_to, op_code, flags, doc,
                 collection=""):
        self.request_id = request_id
        self.response_to = response_to
        self.op_code = op_code
        self.flags = flags
        self.doc = doc
        self.collection = collection


class MongoServiceAdaptor:
    """Command table: ``@svc.command("ping")`` over
    ``def ping(socket, doc) -> reply_doc``. Unknown commands get
    {ok: 0, errmsg, code: 59} (CommandNotFound)."""

    def __init__(self):
        self._handlers: Dict[str, Callable] = {}

    def command(self, name: Optional[str] = None):
        def deco(fn):
            self._handlers[(name or fn.__name__).lower()] = fn
            return fn
        return deco

    def add_command_handler(self, name: str, fn: Callable) -> None:
        self._handlers[name.lower()] = fn

    def find(self, name: str) -> Optional[Callable]:
        return self._handlers.get(name.lower())


def _pack_msg(request_id: int, response_to: int, doc: dict) -> bytes:
    body = struct.pack("<I", 0) + b"\x00" + bson.encode_doc(doc)
    return _HDR.pack(16 + len(body), request_id, response_to, OP_MSG) + body


def _pack_reply(request_id: int, response_to: int, doc: dict) -> bytes:
    # legacy OP_REPLY: flags, cursorId, startingFrom, numberReturned, docs
    body = struct.pack("<iqii", 8, 0, 0, 1) + bson.encode_doc(doc)
    return _HDR.pack(16 + len(body), request_id, response_to, OP_REPLY) + body


class MongoProtocol(Protocol):
    name = "mongo"
    min_probe_bytes = 16   # all-binary header; opcode at offset 12 is the
    #                        only discriminator, so short prefixes are
    #                        tentative disclaimers, not definitive

    def __init__(self):
        self._id_lock = threading.Lock()
        self._next_id = 1

    def _reply_id(self) -> int:
        with self._id_lock:
            rid = self._next_id
            self._next_id += 1
            return rid

    # ---------------------------------------------------------------- parse
    def parse(self, portal, socket) -> Tuple[str, object]:
        head = portal.peek_bytes(min(16, portal.size))
        if len(head) < 16:
            # can't rule ourselves in yet; mongo's header is all-binary so
            # only claim bytes once a full header with a known opcode shows
            return PARSE_TRY_OTHERS, None
        length, request_id, response_to, op_code = _HDR.unpack(head)
        if op_code not in _KNOWN_OPS or length < 16 or length > _MAX_MESSAGE:
            return PARSE_TRY_OTHERS, None
        if portal.size < length:
            return PARSE_NOT_ENOUGH_DATA, None
        portal.pop_front(16)
        payload = portal.cut(length - 16).to_bytes()
        try:
            if op_code == OP_MSG:
                flags = struct.unpack_from("<I", payload, 0)[0]
                if payload[4:5] != b"\x00":
                    raise bson.BsonError("only OP_MSG section kind 0")
                doc, _ = bson.decode_doc(payload, 5)
                return PARSE_OK, MongoMessage(request_id, response_to,
                                              op_code, flags, doc)
            if op_code == OP_QUERY:
                flags = struct.unpack_from("<i", payload, 0)[0]
                end = payload.index(b"\x00", 4)
                collection = payload[4:end].decode("latin1")
                doc, _ = bson.decode_doc(payload, end + 9)  # skip skip/ret
                return PARSE_OK, MongoMessage(request_id, response_to,
                                              op_code, flags, doc,
                                              collection)
            raise bson.BsonError(f"unsupported opcode {op_code}")
        except (bson.BsonError, ValueError, struct.error) as e:
            socket.set_failed(ConnectionError(f"corrupt mongo frame: {e}"))
            return PARSE_NOT_ENOUGH_DATA, None

    # -------------------------------------------------------------- process
    def process_inline(self, msg: MongoMessage, socket) -> bool:
        from brpc_tpu.transport.input_messenger import process_in_parse_order
        process_in_parse_order(socket, "mongo", msg, self._run_command)
        return True

    async def _run_command(self, msg: MongoMessage, socket):
        server = socket.user_data.get("server")
        adaptor: Optional[MongoServiceAdaptor] = (
            getattr(server.options, "mongo_service_adaptor", None)
            if server is not None else None)

        def send(doc: dict):
            packer = _pack_reply if msg.op_code == OP_QUERY else _pack_msg
            out = IOBuf()
            out.append(packer(self._reply_id(), msg.request_id, doc))
            socket.write(out)

        if adaptor is None:
            send({"ok": 0.0, "errmsg": "no mongo_service_adaptor installed",
                  "code": 59})
            return
        if not msg.doc:
            send({"ok": 0.0, "errmsg": "empty command", "code": 59})
            return
        cmd_name = next(iter(msg.doc))
        handler = adaptor.find(cmd_name)
        if handler is None:
            if cmd_name.lower() in ("ismaster", "hello"):
                # minimal topology handshake so drivers proceed
                send({"ok": 1.0, "ismaster": True, "isWritablePrimary": True,
                      "maxWireVersion": 13, "minWireVersion": 0,
                      "maxBsonObjectSize": 16 << 20,
                      "localTime": bson.DateTimeMs(int(time.time() * 1000))})
                return
            send({"ok": 0.0, "errmsg": f"no such command: '{cmd_name}'",
                  "code": 59})
            return
        cost = server.on_request_start(f"mongo.{cmd_name}")
        if not cost:
            send({"ok": 0.0, "errmsg": "max_concurrency reached", "code": 202})
            return
        t0 = time.monotonic_ns()
        error = False
        try:
            r = handler(socket, msg.doc)
            if inspect.isawaitable(r):
                r = await r
            reply = r if isinstance(r, dict) else {"ok": 1.0}
            if "ok" not in reply:
                reply["ok"] = 1.0
        except Exception as e:
            error = True
            reply = {"ok": 0.0, "errmsg": f"handler error: {e}", "code": 8}
        server.on_request_end(f"mongo.{cmd_name}",
                              (time.monotonic_ns() - t0) / 1e3, error, cost)
        send(reply)

    def process(self, msg, socket):
        raise AssertionError("mongo messages are processed inline")


_instance: Optional[MongoProtocol] = None


def ensure_registered() -> MongoProtocol:
    global _instance
    if _instance is None:
        _instance = MongoProtocol()
        register_protocol(_instance)
    return _instance
